"""JobServer multi-tenancy + TaskUnit scheduling tests.

Analogues of the reference's jobserver behavior: submit over the command
channel, run-everywhere scheduling, concurrent jobs interleaved by the
global TaskUnit order, graceful shutdown.
"""
import threading
import time

import numpy as np
import pytest

from harmony_tpu.config.params import JobConfig, TrainerParams
from harmony_tpu.jobserver import (
    FifoExclusiveScheduler,
    JobServer,
    ShareAllScheduler,
    submit_job,
)
from harmony_tpu.jobserver.client import CommandSender
from harmony_tpu.parallel import DevicePool
from harmony_tpu.runtime.taskunit import (
    CPU,
    NET,
    VOID,
    GlobalTaskUnitScheduler,
    LocalTaskUnitScheduler,
    TaskUnitClient,
    TaskUnitInfo,
)


def mlr_job(job_id="mlr", n=256, epochs=3, workers=1, slack=0):
    return JobConfig(
        job_id=job_id,
        app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=epochs,
            num_mini_batches=4,
            clock_slack=slack,
            app_params={
                "num_classes": 4,
                "num_features": 16,
                "features_per_partition": 4,
                "step_size": 0.5,
            },
        ),
        num_workers=workers,
        user={
            "data_fn": "harmony_tpu.apps.mlr:make_synthetic",
            "data_args": {"n": n, "num_features": 16, "num_classes": 4, "seed": 7},
        },
    )


def addvector_job(job_id="addv", n=128, epochs=2, workers=2, slack=1):
    return JobConfig(
        job_id=job_id,
        app_type="dolphin",
        trainer="harmony_tpu.apps.addvector:AddVectorTrainer",
        params=TrainerParams(
            num_epochs=epochs,
            num_mini_batches=4,
            clock_slack=slack,
            app_params={"num_keys": 8, "vector_dim": 2, "delta": 1.0},
        ),
        num_workers=workers,
        user={
            "data_fn": "harmony_tpu.apps.addvector:make_marks",
            "data_args": {"n": n},
        },
    )


class TestTaskUnits:
    def test_weighted_fair_grants_favor_cheap_job(self):
        """Under contention the scheduler meters ONE non-VOID unit at a
        time across jobs, and when several units wait, the lowest
        DEVICE-TIME deficit wins — measured unit seconds, not unit counts
        (count-pacing was the 15x starvation of FAIRNESS_r02)."""
        g = GlobalTaskUnitScheduler()
        g.on_job_start("cheap", ["c0"])
        g.on_job_start("dear", ["d0"])
        g.report_unit_cost("cheap", 0.01)
        g.report_unit_cost("dear", 0.10)
        # one grant each: deficits are now cheap=0.01, dear=0.10 — equal
        # unit COUNTS, very different device-time charges
        u_d0 = TaskUnitInfo("dear", "d0", CPU, 0)
        assert g.wait_ready(u_d0, timeout=5)
        g.on_unit_finished(u_d0)
        # occupy the meter with cheap's unit 0...
        u_c0 = TaskUnitInfo("cheap", "c0", CPU, 0)
        assert g.wait_ready(u_c0, timeout=5)
        granted = []

        def waiter(job, eid, seq):
            u = TaskUnitInfo(job, eid, CPU, seq)
            assert g.wait_ready(u, timeout=10)
            granted.append((job, u))

        # ...then queue dear FIRST (earlier arrival), cheap second
        td = threading.Thread(target=waiter, args=("dear", "d0", 1))
        td.start()
        time.sleep(0.1)
        tc = threading.Thread(target=waiter, args=("cheap", "c0", 1))
        tc.start()
        time.sleep(0.1)
        assert granted == []  # meter: nothing granted while u_c0 runs
        g.on_unit_finished(u_c0)
        tc.join(timeout=10)
        assert [j for j, _ in granted] == ["cheap"]  # deficit beats arrival
        assert td.is_alive()  # dear still metered out
        g.on_unit_finished(granted[0][1])
        td.join(timeout=10)
        assert [j for j, _ in granted] == ["cheap", "dear"]
        g.on_job_finish("cheap")
        g.on_job_finish("dear")

    def test_quorum_grant_and_global_order(self):
        g = GlobalTaskUnitScheduler()
        g.on_job_start("j", ["e0", "e1"])
        granted = []

        def worker(eid):
            g.wait_ready(TaskUnitInfo("j", eid, CPU, 0), timeout=5)
            granted.append(eid)

        t0 = threading.Thread(target=worker, args=("e0",))
        t0.start()
        time.sleep(0.1)
        assert granted == []  # quorum incomplete: e0 must wait for e1
        t1 = threading.Thread(target=worker, args=("e1",))
        t1.start()
        t0.join(timeout=5)
        t1.join(timeout=5)
        assert sorted(granted) == ["e0", "e1"]
        assert g.grant_order() == [("j", 0, CPU)]

    def test_unregistered_job_passes_through(self):
        g = GlobalTaskUnitScheduler()
        assert g.wait_ready(TaskUnitInfo("ghost", "e", CPU, 0), timeout=1)

    def test_local_slots_bound_concurrency(self):
        local = LocalTaskUnitScheduler(cpu_slots=1, net_slots=2)
        running = {"CPU": 0, "max": 0}
        lock = threading.Lock()

        def use(kind):
            local.acquire(kind)
            with lock:
                running["CPU"] += 1
                running["max"] = max(running["max"], running["CPU"])
            time.sleep(0.05)
            with lock:
                running["CPU"] -= 1
            local.release(kind)

        ts = [threading.Thread(target=use, args=(CPU,)) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert running["max"] == 1  # one CPU slot

    def test_client_scope_sequences(self):
        g = GlobalTaskUnitScheduler()
        local = LocalTaskUnitScheduler()
        g.on_job_start("j", ["e0"])
        c = TaskUnitClient("j", "e0", g, local)
        with c.scope(CPU):
            pass
        with c.scope(NET):
            pass
        assert [k for (_, _, k) in g.grant_order()] == [CPU, NET]


class TestJobServer:
    def test_single_job_end_to_end(self, devices):
        server = JobServer(4, device_pool=DevicePool(devices[:4]))
        server.start()
        fut = server.submit(mlr_job())
        result = fut.result(timeout=120)
        assert "mlr/w0" in result["workers"]
        losses = result["workers"]["mlr/w0"]["losses"]
        assert losses[-1] < losses[0]
        server.shutdown()
        assert server.state == "CLOSED"
        # job-owned table dropped at cleanup
        assert server.master.table_ids() == []

    def test_concurrent_multitenant_jobs(self, devices):
        """MLR + AddVector concurrently on the SAME executors (ShareAll),
        TaskUnit-scheduled; both finish correct."""
        server = JobServer(4, device_pool=DevicePool(devices[:4]))
        server.start()
        f1 = server.submit(mlr_job(workers=2, slack=1, epochs=2))
        f2 = server.submit(addvector_job(workers=2, slack=1))
        r1 = f1.result(timeout=180)
        r2 = f2.result(timeout=180)
        assert len(r1["workers"]) == 2 and len(r2["workers"]) == 2
        grants = server.global_taskunit.grant_order()
        jobs_in_order = {j for (j, _, _) in grants}
        assert jobs_in_order == {"mlr", "addv"}  # both flowed through one order
        server.shutdown()

    def test_addvector_exact_with_multitenancy(self, devices):
        """Exact final table contents, validated via the shared-table path:
        pre-creating the table under the explicit id means the job reuses it
        (not owns it), so it survives job cleanup for inspection."""
        from harmony_tpu.config.params import TableConfig

        server = JobServer(4, device_pool=DevicePool(devices[:4]))
        server.start()
        n, epochs, workers = 128, 2, 2
        shared_cfg = TableConfig(
            table_id="shared-addv", capacity=8, value_shape=(2,), num_blocks=8
        )
        server.master.create_table(shared_cfg, server.master.executor_ids())
        job = addvector_job(n=n, epochs=epochs, workers=workers)
        job = job.replace(tables=[shared_cfg])
        server.submit(job).result(timeout=120)
        vals = np.asarray(server.master.get_table("shared-addv").table.pull_array())
        np.testing.assert_allclose(vals, np.full((8, 2), n * epochs))
        server.shutdown()

    def test_two_same_app_jobs_do_not_share_model(self, devices):
        """Two concurrent MLR jobs with trainer-default table ids must get
        PRIVATE (job-namespaced) model tables."""
        server = JobServer(4, device_pool=DevicePool(devices[:4]))
        server.start()
        seen_tables = set()
        f1 = server.submit(mlr_job("dup-app-a", epochs=2))
        f2 = server.submit(mlr_job("dup-app-b", epochs=2))
        deadline = time.time() + 60
        while time.time() < deadline and (not f1.done() or not f2.done()):
            seen_tables.update(server.master.table_ids())
            time.sleep(0.01)
        f1.result(timeout=60)
        f2.result(timeout=60)
        assert "dup-app-a:mlr-model" in seen_tables
        assert "dup-app-b:mlr-model" in seen_tables
        server.shutdown()

    def test_worker_crash_does_not_deadlock_taskunits(self, devices):
        """w0 dies during init; w1 must finish (quorum shrinks) and the job
        future must resolve with the error instead of hanging."""
        server = JobServer(2, device_pool=DevicePool(devices[:2]))
        server.start()
        job = addvector_job("crashy", workers=2)
        job = job.replace(trainer="tests.helpers:CrashOnW0Trainer")
        fut = server.submit(job)
        with pytest.raises(RuntimeError, match="synthetic failure"):
            fut.result(timeout=60)
        server.shutdown(timeout=60)
        assert server.state == "CLOSED"

    def test_resubmit_after_completion_allowed(self, devices):
        server = JobServer(2, device_pool=DevicePool(devices[:2]))
        server.start()
        server.submit(mlr_job("again", epochs=1)).result(timeout=120)
        server.submit(mlr_job("again", epochs=1)).result(timeout=120)
        server.shutdown()

    def test_fifo_scheduler_serializes(self, devices):
        server = JobServer(
            4, scheduler=FifoExclusiveScheduler(), device_pool=DevicePool(devices[:4])
        )
        server.start()
        seen = []
        orig_launch = server._launch

        def tracking_launch(cfg, execs):
            seen.append((cfg.job_id, time.perf_counter()))
            orig_launch(cfg, execs)

        server._scheduler._launch = tracking_launch
        f1 = server.submit(mlr_job("fifo-a", epochs=2))
        f2 = server.submit(mlr_job("fifo-b", epochs=1))
        f1.result(timeout=120)
        f2.result(timeout=120)
        assert [s[0] for s in seen] == ["fifo-a", "fifo-b"]
        server.shutdown()

    def test_duplicate_job_id_rejected(self, devices):
        server = JobServer(2, device_pool=DevicePool(devices[:2]))
        server.start()
        f = server.submit(mlr_job("dup", epochs=1))
        with pytest.raises(ValueError):
            server.submit(mlr_job("dup"))
        f.result(timeout=120)
        server.shutdown()


class TestTcpControlPlane:
    def test_submit_status_shutdown_over_tcp(self, devices):
        server = JobServer(2, device_pool=DevicePool(devices[:2]))
        server.start()
        port = server.serve_tcp()
        sender = CommandSender(port)
        reply = submit_job(mlr_job("tcp-job", epochs=1), port)
        assert reply["job_id"] == "tcp-job"
        status = sender.send_status_command()
        assert status["ok"] and status["state"] == "INIT"
        # wait for the job then shut down over the wire
        deadline = time.time() + 120
        while server.running_jobs() and time.time() < deadline:
            time.sleep(0.1)
        assert sender.send_shutdown_command()["ok"]
        deadline = time.time() + 30
        while server.state != "CLOSED" and time.time() < deadline:
            time.sleep(0.05)
        assert server.state == "CLOSED"

    def test_bad_command_gets_error_reply(self, devices):
        server = JobServer(2, device_pool=DevicePool(devices[:2]))
        server.start()
        port = server.serve_tcp()
        reply = CommandSender(port)._roundtrip({"command": "NOPE"})
        assert not reply["ok"] and "unknown command" in reply["error"]
        server.shutdown()


class TestFailureIsolation:
    def test_failed_job_does_not_poison_tenants(self, devices):
        """A job that dies fails ITS future; a concurrent healthy job and a
        subsequently submitted job both complete, and the server stays
        open for business (ref stance §5.3: fail fast per job — here
        per-job, not per-server)."""
        import pytest as _pytest

        from harmony_tpu.config.params import JobConfig, TrainerParams
        from harmony_tpu.jobserver.server import JobServer

        def cfg(job_id, trainer, data_fn):
            return JobConfig(
                job_id=job_id, app_type="dolphin", trainer=trainer,
                params=TrainerParams(num_epochs=2, num_mini_batches=2,
                                     app_params={"num_keys": 4}),
                num_workers=2,
                user={"data_fn": data_fn, "data_args": {"n": 64}},
            )

        server = JobServer(num_executors=4)
        server.start()
        try:
            bad = server.submit(cfg(
                "boom", "tests.helpers:ExplodingTrainer",
                "harmony_tpu.apps.addvector:make_marks"))
            good = server.submit(cfg(
                "good", "harmony_tpu.apps.addvector:AddIntegerTrainer",
                "harmony_tpu.apps.addvector:make_marks"))
            with _pytest.raises(RuntimeError, match="injected failure"):
                bad.result(timeout=120)
            result = good.result(timeout=120)
            assert len(result["workers"]) == 2
            # the server remains healthy: a post-failure submission succeeds
            late = server.submit(cfg(
                "late", "harmony_tpu.apps.addvector:AddIntegerTrainer",
                "harmony_tpu.apps.addvector:make_marks"))
            assert late.result(timeout=120)["workers"]
            assert server.state != "CLOSED"
        finally:
            server.shutdown(timeout=60)


class TestCarveScheduler:
    def test_disjoint_slices_and_queueing(self):
        """Protocol-level (fake launch): slices are disjoint, arrivals
        without min_slice free executors queue, finish re-launches."""
        from harmony_tpu.jobserver.scheduler import CarveScheduler

        launched = {}
        sched = CarveScheduler(min_slice=2)
        sched.bind([f"e{i}" for i in range(8)],
                   lambda cfg, exs: launched.__setitem__(cfg.job_id, exs))
        sched.on_job_arrival(mlr_job("a"))
        assert len(launched["a"]) == 8  # fair share at arrival = 8 // 1
        sched.on_job_arrival(mlr_job("b"))
        assert "b" not in launched  # pool exhausted -> queued
        sched.on_job_finish("a")
        assert len(launched["b"]) >= 2  # freed slice launches the queue
        assert set(launched["b"]) <= {f"e{i}" for i in range(8)}

    def test_fair_share_carving(self):
        from harmony_tpu.jobserver.scheduler import CarveScheduler

        launched = {}
        sched = CarveScheduler(min_slice=1)
        sched.bind([f"e{i}" for i in range(8)],
                   lambda cfg, exs: launched.__setitem__(cfg.job_id, exs))
        # Drive arrivals while slices shrink: 8//1=8 for the first job, so
        # use finish/arrive interleaving to observe carving at various loads
        sched.on_job_arrival(mlr_job("a"))
        sched.on_job_finish("a")
        sched.on_job_arrival(mlr_job("b"))  # 8 free again
        launched.clear()
        sched.on_job_arrival(mlr_job("c"))  # 0 free -> queue
        assert "c" not in launched
        sched.on_job_finish("b")            # frees 8, c gets 8//1=8
        assert len(launched["c"]) == 8
        assert sorted(sched.slice_of("c")) == sorted(launched["c"])

    def test_jobserver_integration_disjoint(self, devices):
        """Two concurrent jobs under carve scheduling run on disjoint
        executor slices and both complete with exact sums."""
        from harmony_tpu.jobserver.scheduler import CarveScheduler

        sched = CarveScheduler(min_slice=4, max_share=4)
        server = JobServer(8, scheduler=sched, device_pool=DevicePool(devices))
        server.start()
        try:
            fa = server.submit(addvector_job("carve-a", workers=1, slack=0))
            fb = server.submit(addvector_job("carve-b", workers=1, slack=0))
            slices = {}
            deadline = time.time() + 30
            while time.time() < deadline and (
                not sched.slice_of("carve-a") or not sched.slice_of("carve-b")
            ):
                time.sleep(0.05)
            slices["a"] = set(sched.slice_of("carve-a"))
            slices["b"] = set(sched.slice_of("carve-b"))
            ra, rb = fa.result(timeout=120), fb.result(timeout=120)
            assert slices["a"] and slices["b"] and not (slices["a"] & slices["b"])
        finally:
            server.shutdown(timeout=60)

    def test_max_share_allows_concurrency(self):
        from harmony_tpu.jobserver.scheduler import CarveScheduler

        launched = {}
        sched = CarveScheduler(min_slice=2, max_share=4)
        sched.bind([f"e{i}" for i in range(8)],
                   lambda cfg, exs: launched.__setitem__(cfg.job_id, exs))
        sched.on_job_arrival(mlr_job("a"))
        sched.on_job_arrival(mlr_job("b"))
        assert len(launched["a"]) == 4 and len(launched["b"]) == 4
        assert not set(launched["a"]) & set(launched["b"])

    def test_resource_change_reconciles_pool(self):
        from harmony_tpu.jobserver.scheduler import CarveScheduler

        launched = {}
        sched = CarveScheduler(min_slice=2, max_share=4)
        sched.bind([f"e{i}" for i in range(8)],
                   lambda cfg, exs: launched.__setitem__(cfg.job_id, exs))
        sched.on_job_arrival(mlr_job("a"))           # takes e0..e3
        # e4..e7 depart; e8..e9 arrive
        sched.on_resource_change(launched["a"] + ["e8", "e9"])
        sched.on_job_arrival(mlr_job("b"))
        assert set(launched["b"]) == {"e8", "e9"}    # never the departed ones
        sched.on_job_finish("a")                     # a's slice still known
        sched.on_job_arrival(mlr_job("c"))
        assert set(launched["c"]) <= set(launched["a"])


class TestDeferredModelEval:
    """Deferred model evaluation at graceful shutdown (ref: JobServerDriver
    shutdown runs deferred evaluation over the ModelChkpManager chain,
    JobServerDriver.java:178-214 + DolphinMaster.evaluate())."""

    def _job(self, tmp_path, epochs=3):
        cfg = mlr_job("eval-mlr", n=256, epochs=epochs, workers=1)
        cfg.params.model_chkp_period = 1
        cfg.params.offline_model_eval = True
        return cfg

    def test_chain_and_eval_at_shutdown(self, devices, tmp_path):
        server = JobServer(2, device_pool=DevicePool(devices[:2]),
                           chkp_root=str(tmp_path))
        server.start()
        cfg = self._job(tmp_path, epochs=3)
        res = server.submit(cfg).result(timeout=300)
        assert len(res["model_chkp_ids"]) == 3  # one snapshot per epoch
        assert "eval-mlr" not in server.eval_results  # deferred, not yet run
        server.shutdown(timeout=300)
        evals = server.eval_results["eval-mlr"]
        assert isinstance(evals, list) and len(evals) == 3
        # training progress is visible across the replayed chain: the last
        # snapshot must beat the first on training-set loss
        assert evals[-1]["loss"] < evals[0]["loss"]
        assert all(np.isfinite(m["loss"]) for m in evals)
        # replay consumes the chain: the disk is reclaimed
        import os

        root = os.path.join(str(tmp_path), "eval-mlr")
        leftovers = [
            d for sub in ("temp", "commit")
            for d in os.listdir(os.path.join(root, sub))
            if os.path.isdir(os.path.join(root, sub, d))
        ]
        assert leftovers == []

    def test_no_chain_without_period(self, devices, tmp_path):
        server = JobServer(2, device_pool=DevicePool(devices[:2]),
                           chkp_root=str(tmp_path))
        server.start()
        res = server.submit(mlr_job("plain", n=128, epochs=1, workers=1)).result(
            timeout=300
        )
        assert "model_chkp_ids" not in res
        server.shutdown(timeout=300)
        assert server.eval_results == {}

    def test_eval_failure_recorded_not_raised(self, devices, tmp_path):
        server = JobServer(2, device_pool=DevicePool(devices[:2]),
                           chkp_root=str(tmp_path))
        server.start()
        cfg = self._job(tmp_path, epochs=1)
        # break the deferred eval's data source AFTER training uses it: the
        # test_data_fn path resolves lazily inside the closure
        cfg.user["test_data_fn"] = "harmony_tpu.apps.mlr:no_such_fn"
        server.submit(cfg).result(timeout=300)
        server.shutdown(timeout=300)
        assert "error" in server.eval_results["eval-mlr"]


class TestSharedTableLifetime:
    def test_creator_finishing_first_does_not_kill_tenant(self, devices):
        """Two jobs share one model table by id; the CREATOR finishes long
        before the tenant. Storage must survive until the LAST user releases
        (master refcount) — previously the creator's cleanup deleted the
        buffers under the still-training tenant."""
        from harmony_tpu.config.params import TableConfig

        server = JobServer(2, device_pool=DevicePool(devices[:2]))
        server.start()
        shared = TableConfig(table_id="life-m", capacity=16,
                             value_shape=(4,), num_blocks=8)

        def job(jid, epochs):
            cfg = mlr_job(jid, n=64, epochs=epochs, workers=1)
            cfg.tables = [shared]
            return cfg

        fa = server.submit(job("life-a", epochs=1))   # creator: done fast
        fa.result(timeout=300)
        # creator already finished and released; tenant must still be able
        # to ATTACH (refcount went 1 -> 0 would have dropped it... the
        # sequential case recreates; the concurrent case is the real race)
        fb = server.submit(job("life-b", epochs=3))
        fc = server.submit(job("life-c", epochs=6))   # overlapping tenants
        rb, rc = fb.result(timeout=300), fc.result(timeout=300)
        server.shutdown(timeout=60)
        for r in (rb, rc):
            losses = next(iter(r["workers"].values()))["losses"]
            assert np.isfinite(losses).all()
        # fully released at the end: a later server could recreate the id
        assert "life-m" not in server.master.table_ids()


class TestJobLogger:
    def test_per_job_prefixed_log_lines(self, devices, caplog):
        """Operator-facing lifecycle logging carries a [JobId: x] prefix on
        every job-scoped line (ref: jobserver/JobLogger.java:34-75), so a
        multi-tenant server's interleaved log stays attributable."""
        import logging

        with caplog.at_level(logging.INFO, logger="harmony_tpu.jobserver"):
            server = JobServer(1, device_pool=DevicePool(devices[:1]))
            server.start()
            cfg = addvector_job("logged", n=32, epochs=1, workers=1, slack=0)
            server.submit(cfg).result(timeout=300)
            server.shutdown(timeout=60)
        msgs = [r.getMessage() for r in caplog.records]
        for want in ("submitted", "dispatched", "training", "finished"):
            assert any(m.startswith(f"[JobId: logged] {want}") for m in msgs), (
                want, msgs)
        assert any(m.startswith("jobserver up") for m in msgs)
        assert any(m.startswith("shutdown initiated") for m in msgs)


class TestPodFastFail:
    def test_broken_pod_fails_dispatch_fast(self, devices):
        """Once the pod is poisoned (partial broadcast / wedged follower),
        later dispatches must fail in milliseconds with a restart
        instruction — not hang in collectives that can never complete."""
        from harmony_tpu.jobserver.pod import PodJobServer

        server = PodJobServer(1, device_pool=DevicePool(devices[:1]),
                              num_followers=1)
        server.start()

        class _FakeConn:
            def close(self):
                pass

        server._followers[1] = (_FakeConn(), None)
        server._pod_broken = "simulated wedged follower"
        fut = server.submit(addvector_job("podfail", n=32, epochs=1,
                                          workers=1, slack=0))
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="pod is broken"):
            fut.result(timeout=60)
        assert time.monotonic() - t0 < 5.0
        server._followers.clear()
        server.shutdown(timeout=30)

    def test_lockstep_multiworker_exact_sums(self, devices):
        """The DispatchTurnstile schedule (what makes multi-worker SSP
        legal on a multi-process pod — the old submit-time rejection is
        gone) preserves push exactness: an AddVector job with two workers
        under force_lockstep lands every push exactly once, and twice in a
        row produces the same deterministic grant order. (The pod e2e leg
        lives in test_multihost.py; this is the in-process half.)"""
        from harmony_tpu.config.params import TableConfig

        server = JobServer(4, device_pool=DevicePool(devices[:4]))
        server.start()
        n, epochs = 64, 2
        shared_cfg = TableConfig(
            table_id="lockstep-addv", capacity=8, value_shape=(2,),
            num_blocks=8, update_fn="add",
        )
        server.master.create_table(shared_cfg, server.master.executor_ids())
        cfg = addvector_job("lockstep", n=n, epochs=epochs, workers=2,
                            slack=1).replace(tables=[shared_cfg])
        cfg.user["force_lockstep"] = True
        res = server.submit(cfg).result(timeout=120)
        assert set(res["workers"]) == {"lockstep/w0", "lockstep/w1"}
        vals = np.asarray(
            server.master.get_table("lockstep-addv").table.pull_array()
        )
        # both workers' pushes all landed, exactly once each
        np.testing.assert_allclose(vals, np.full((8, 2), n * epochs))
        server.shutdown(timeout=30)


class TestPodFollower:
    def test_follower_protocol_and_error_reporting(self, devices):
        """Drive a PodFollower with a scripted leader socket: JOIN arrives,
        a RUN_JOB naming executors the follower does not have yields a
        JOB_DONE error report (never a crash or a hang), and SHUTDOWN ends
        the loop."""
        import json as _json
        import socket as _socket
        import threading as _threading

        from harmony_tpu.jobserver.pod import PodFollower

        lsock = _socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(1)
        port = lsock.getsockname()[1]
        box = {}

        def leader():
            conn, _ = lsock.accept()
            f = conn.makefile("r")
            box["join"] = _json.loads(f.readline())
            cfg = mlr_job("pod-missing", n=64, epochs=1, workers=1)
            conn.sendall((_json.dumps({
                "cmd": "RUN_JOB", "conf": cfg.to_dict(),
                "executor_ids": ["executor-does-not-exist"],
            }) + "\n").encode())
            box["done"] = _json.loads(f.readline())
            conn.sendall(b'{"cmd": "SHUTDOWN"}\n')
            conn.close()

        t = _threading.Thread(target=leader, daemon=True)
        t.start()
        follower = PodFollower("127.0.0.1", port, pid=3, num_executors=1)
        follower.run()  # returns on SHUTDOWN
        t.join(timeout=30)
        assert box["join"] == {"cmd": "JOIN", "pid": 3}
        done = box["done"]
        assert done["cmd"] == "JOB_DONE" and done["pid"] == 3
        assert not done["ok"]
        assert "missing executors" in done["error"]


class TestJobOptimizerLoop:
    def test_job_reconfigures_itself_mid_training(self, devices):
        """JobConfig.optimizer wires the per-job elasticity loop (the
        reference's ETOptimizationOrchestrator run by the driver): a canned
        add-one-server optimizer forces a live migration WHILE the job
        trains under the JobServer; training stays correct and the result
        reports the reconfiguration."""
        server = JobServer(2, device_pool=DevicePool(devices[:4]))
        server.start()
        cfg = addvector_job("opt-addv", n=128, epochs=6, workers=1, slack=0)
        cfg.optimizer = "add_one_server"
        cfg.optimizer_period = 0.2
        result = server.submit(cfg).result(timeout=300)
        assert result.get("reconfigs", 0) >= 1, result
        server.shutdown(timeout=60)

    def test_homogeneous_optimizer_runs_quietly(self, devices):
        """The real cost-model optimizer (not a canned plan) runs on live
        metrics without breaking training; with a tiny balanced job it may
        or may not reconfigure, but the job must stay correct."""
        server = JobServer(2, device_pool=DevicePool(devices[:2]))
        server.start()
        cfg = mlr_job("opt-mlr", n=256, epochs=4, workers=1)
        cfg.optimizer = "homogeneous"
        cfg.optimizer_period = 0.2
        result = server.submit(cfg).result(timeout=300)
        losses = result["workers"]["opt-mlr/w0"]["losses"]
        assert losses[-1] < losses[0]
        server.shutdown(timeout=60)

    def test_lease_released_when_orchestrator_construction_fails(self, devices):
        """If optimizer resolution/construction raises AFTER the exclusive
        lease is acquired, the lease must be released — otherwise every
        resubmission of the job silently trains unoptimized."""
        from harmony_tpu.config.params import TableConfig
        from harmony_tpu.jobserver.entity import DolphinJobEntity
        from harmony_tpu.runtime.master import ETMaster

        master = ETMaster(DevicePool(devices[:1]))
        execs = master.add_executors(1)
        handle = master.create_table(
            TableConfig(table_id="leak", capacity=8, value_shape=(2,),
                        num_blocks=2),
            [execs[0].id],
        )
        cfg = JobConfig(job_id="leak-job", app_type="dolphin",
                        trainer="harmony_tpu.apps.mlr:MLRTrainer",
                        params=TrainerParams(),
                        optimizer="no.such.module:Opt")
        ent = DolphinJobEntity(cfg, metric_manager=object())
        ent._master = master
        ent._handle = handle
        with pytest.raises(ModuleNotFoundError):
            ent._make_orchestrator()
        assert master.acquire_optimizer_lease(handle.table_id)
        master.release_optimizer_lease(handle.table_id)

    def test_one_jobs_reconfig_does_not_erase_tenant_metrics(self, devices):
        """Job A's optimizer migrates A's table mid-run; job B's metrics
        (and its exact ServerMetrics accounting) must survive untouched —
        reconfiguration cleanup is scoped to the reconfiguring job."""
        server = JobServer(2, device_pool=DevicePool(devices[:4]))
        server.start()
        a = addvector_job("iso-a", n=128, epochs=6, workers=1, slack=0)
        a.optimizer = "add_one_server"
        a.optimizer_period = 0.1
        b = mlr_job("iso-b", n=256, epochs=4, workers=1)
        ra = server.submit(a)
        rb = server.submit(b)
        res_a, res_b = ra.result(timeout=300), rb.result(timeout=300)
        assert res_a.get("reconfigs", 0) >= 1, res_a
        assert "optimizer_errors" not in res_a, res_a
        # B's per-job accounting stayed exact despite A's migrations
        b_pulls = sum(m.pull_count for m in server.metrics.server_metrics(job_id="iso-b"))
        assert b_pulls == 4 * 4  # 4 epochs x 4 batches
        # and B's batch series survived the reconfig window
        assert server.metrics.worker_batch_metrics(job_id="iso-b")
        server.shutdown(timeout=60)
