"""Worker process for the two-process multihost integration test.

Launched twice by tests/test_multihost.py with JAX_PLATFORMS=cpu and 4
virtual devices per process; the pair forms one jax.distributed job whose
GLOBAL device list has 8 devices. Prints `RESULT <json>` for the parent
to compare across processes.

Usage: python multihost_worker.py <coordinator> <num_processes> <pid>
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coordinator, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    from harmony_tpu.parallel import multihost

    assert multihost.initialize_distributed(coordinator, nprocs, pid)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    assert multihost.process_count() == nprocs, multihost.process_count()
    devices = multihost.global_devices()
    assert len(devices) == 4 * nprocs, devices

    # 1. a psum over the full global mesh (the DCN+ICI data plane)
    mesh = multihost.global_mesh(data=len(devices))
    total = jax.jit(
        jax.shard_map(
            lambda x: jax.lax.psum(x, "data"),
            mesh=mesh,
            in_specs=P("data"),
            out_specs=P(),
        )
    )(np.ones((len(devices),), np.float32))
    psum_val = float(np.asarray(total)[0])

    # 2. one sequence-parallel LM train step over a (data=2, seq=4) global
    # mesh — every process passes the SAME full token batch; jax shards it.
    from harmony_tpu.models import TransformerConfig, TransformerLM, make_lm_data
    from harmony_tpu.models.transformer import make_sp_train_step
    from harmony_tpu.parallel import build_mesh

    sp_mesh = build_mesh(devices, data=2, seq=4, model=1)
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, max_seq=64, attn="blockwise")
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(make_lm_data(4, 64, cfg.vocab_size, seed=1))
    step = make_sp_train_step(model, sp_mesh, learning_rate=0.1)
    new_params, loss = step(params, tokens)
    # params come back replicated: every process can read its local copy
    first_leaf = np.asarray(
        jax.tree.leaves(new_params)[0].addressable_data(0)
    )

    # 3. sparse hash table sharded over the global mesh: one fused
    # getOrInit pull + push with identical replicated inputs; admissions,
    # drops, and the value checksum must agree across processes.
    from jax.sharding import NamedSharding
    from harmony_tpu.config import TableConfig
    from harmony_tpu.parallel.mesh import MODEL_AXIS
    from harmony_tpu.table import HashTableSpec

    hspec = HashTableSpec(TableConfig(
        table_id="mh", capacity=1024, value_shape=(8,),
        num_blocks=len(devices), is_ordered=False, sparse=True,
    ))
    hmesh = build_mesh(devices, data=1, model=len(devices))
    hsh = NamedSharding(hmesh, P(MODEL_AXIS))
    rng = np.random.default_rng(7)
    hkeys = jnp.asarray(
        rng.choice(2**31 - 3, size=256, replace=False) + 1, jnp.int32
    )
    hdeltas = jnp.asarray(rng.standard_normal((256, 8)), jnp.float32)

    @jax.jit
    def hash_step(keys, deltas):
        state = jax.lax.with_sharding_constraint(hspec.init_state(), (hsh, hsh))
        state, vals, token = hspec.pull(state, keys)
        state = hspec.push(state, token, deltas)
        return (
            jnp.sum(state[0] < 0),
            jnp.sum(state[1]),
            jnp.sum(~token[2]),
        )

    present, vsum, dropped = hash_step(hkeys, hdeltas)

    multihost.sync_global_devices("test-barrier")
    print("RESULT " + json.dumps({
        "pid": pid,
        "psum": psum_val,
        "loss": round(float(np.asarray(loss.addressable_data(0))), 6),
        "leaf0": round(float(first_leaf.ravel()[0]), 6),
        "hash_present": int(np.asarray(present.addressable_data(0))),
        "hash_sum": round(float(np.asarray(vsum.addressable_data(0))), 4),
        "hash_dropped": int(np.asarray(dropped.addressable_data(0))),
    }))


if __name__ == "__main__":
    main()
