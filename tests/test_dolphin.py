"""End-to-end Dolphin training tests on the virtual 8-device mesh.

The analogues of the reference's integration tests (SURVEY.md §4): run a
full app and assert exact values (AddVector/AddInteger) or learning progress
(MLR loss decreasing), as `ExampleTest`/`ValidatorTask` do on the REEF local
runtime.
"""
import numpy as np

from harmony_tpu.apps.addvector import AddIntegerTrainer, AddVectorTrainer, make_marks
from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic
from harmony_tpu.config.params import TrainerParams
from harmony_tpu.dolphin import TrainingDataProvider, TrainerContext, WorkerTasklet
from harmony_tpu.table import DenseTable, TableSpec


def run_job(trainer, data_arrays, mesh, params, job_id="job"):
    spec = TableSpec(trainer.model_table_config())
    table = DenseTable(spec, mesh)
    ctx = TrainerContext(params=params, model_table=table)
    data = TrainingDataProvider(data_arrays, params.num_mini_batches)
    worker = WorkerTasklet(job_id, ctx, trainer, data, mesh)
    result = worker.run()
    return table, worker, result


class TestAddVector:
    def test_exact_sums(self, mesh8):
        n, keys, dim = 256, 32, 4
        trainer = AddVectorTrainer(num_keys=keys, vector_dim=dim, delta=0.5)
        params = TrainerParams(num_epochs=3, num_mini_batches=8)
        table, _, result = run_job(trainer, list(make_marks(n)), mesh8, params)
        expected = trainer.expected_value(n * 3)
        vals = np.asarray(table.pull_array())
        np.testing.assert_allclose(vals, np.full((keys, dim), expected))
        assert result["epochs_run"] == 3

    def test_addinteger_exact(self, mesh_dp):
        # ref scale: 128 updates total (ExampleTest AddIntegerET).
        n = 128
        trainer = AddIntegerTrainer(num_keys=8, delta=1.0)
        params = TrainerParams(num_epochs=1, num_mini_batches=4)
        table, _, _ = run_job(trainer, list(make_marks(n)), mesh_dp, params)
        np.testing.assert_allclose(np.asarray(table.pull_array()), np.full(8, 128.0))


class TestMLR:
    def test_loss_decreases_and_learns(self, mesh8):
        x, y = make_synthetic(512, num_features=32, num_classes=4, seed=1)
        trainer = MLRTrainer(
            num_classes=4, num_features=32, features_per_partition=8, step_size=0.5
        )
        params = TrainerParams(num_epochs=8, num_mini_batches=8)
        table, worker, result = run_job(trainer, [x, y], mesh8, params)
        losses = result["losses"]
        assert losses[-1] < losses[0] * 0.7, losses
        ev = worker.evaluate((x, y))
        assert ev["accuracy"] > 0.8, ev

    def test_resume_from_starting_epoch(self, mesh8):
        x, y = make_synthetic(128, num_features=16, num_classes=2, seed=2)
        trainer = MLRTrainer(num_classes=2, num_features=16, features_per_partition=4)
        params = TrainerParams(num_epochs=4, num_mini_batches=4)
        spec = TableSpec(trainer.model_table_config())
        from harmony_tpu.table import DenseTable

        table = DenseTable(spec, mesh8)
        ctx = TrainerContext(params=params, model_table=table)
        data = TrainingDataProvider([x, y], 4)
        w = WorkerTasklet("j", ctx, trainer, data, mesh8, starting_epoch=2)
        result = w.run()
        assert result["epochs_run"] == 2  # epochs 2,3 only (resume semantics)


class TestMetrics:
    def test_batch_metrics_emitted(self, mesh8):
        from harmony_tpu.metrics import MetricCollector, MetricManager

        manager = MetricManager()
        manager.start_collection()
        collector = MetricCollector(sink=manager.on_metric)
        x, y = make_synthetic(128, num_features=16, num_classes=2)
        trainer = MLRTrainer(num_classes=2, num_features=16, features_per_partition=4)
        params = TrainerParams(num_epochs=2, num_mini_batches=4)
        spec = TableSpec(trainer.model_table_config())
        table = DenseTable(spec, mesh8)
        ctx = TrainerContext(params=params, model_table=table)
        w = WorkerTasklet(
            "j", ctx, trainer, TrainingDataProvider([x, y], 4), mesh8, collector=collector
        )
        w.run()
        batches = manager.worker_batch_metrics()
        assert len(batches) == 8  # 2 epochs x 4 batches
        assert all(b.num_examples == 32 for b in batches)
        assert manager.aggregate_throughput() > 0


class TestEpochWindow:
    """Multi-epoch fused dispatch windows (WorkerTasklet._run_fused_epochs):
    one drain per window must change NOTHING observable — same losses, same
    final model, same per-epoch metric stream — vs the one-drain-per-epoch
    loop, including epoch-indexed trainer hooks (MLR's LR decay)."""

    def _run(self, mesh8, window):
        from harmony_tpu.metrics import MetricCollector, MetricManager

        manager = MetricManager()
        manager.start_collection()
        x, y = make_synthetic(128, num_features=16, num_classes=2, seed=3)
        trainer = MLRTrainer(
            num_classes=2, num_features=16, features_per_partition=4,
            step_size=0.1, decay_rate=0.5, decay_period=2,
        )
        params = TrainerParams(num_epochs=6, num_mini_batches=4,
                               comm_probe_period=0)
        spec = TableSpec(trainer.model_table_config())
        table = DenseTable(spec, mesh8)
        ctx = TrainerContext(params=params, model_table=table)
        w = WorkerTasklet(
            "j", ctx, trainer, TrainingDataProvider([x, y], 4), mesh8,
            collector=MetricCollector(sink=manager.on_metric),
        )
        w.EPOCH_WINDOW = window  # instance override of the class cap
        result = w.run()
        return result, manager, np.asarray(table.pull_array())

    def test_window_matches_unwindowed(self, mesh8):
        r1, m1, t1 = self._run(mesh8, window=1)
        rw, mw, tw = self._run(mesh8, window=8)
        np.testing.assert_allclose(r1["losses"], rw["losses"], rtol=0, atol=0)
        np.testing.assert_allclose(t1, tw, rtol=0, atol=0)
        assert len(m1.worker_batch_metrics()) == len(mw.worker_batch_metrics()) == 24
        e1 = sorted(e.epoch_idx for el in m1._epoch.values() for e in el)
        ew = sorted(e.epoch_idx for el in mw._epoch.values() for e in el)
        assert e1 == ew == list(range(6))

    def test_window_gating(self, mesh8):
        x, y = make_synthetic(64, num_features=8, num_classes=2)
        trainer = MLRTrainer(num_classes=2, num_features=8,
                             features_per_partition=4)
        spec = TableSpec(trainer.model_table_config())
        table = DenseTable(spec, mesh8)

        def worker(probe_period, **kw):
            params = TrainerParams(num_epochs=12, num_mini_batches=4,
                                   comm_probe_period=probe_period)
            ctx = TrainerContext(params=params, model_table=table)
            return WorkerTasklet("j", ctx, trainer,
                                 TrainingDataProvider([x, y], 4), mesh8, **kw)

        # a probe (re)build is due before the first probe ran: per-epoch
        assert worker(4)._epoch_window_len(0, 12) == 1
        # probes off: the class cap applies
        assert worker(0)._epoch_window_len(0, 12) == 8
        # after the first probe, windows open up to the drift-refresh
        # horizon (8x period), clamped by the class cap
        w = worker(4)
        w._probe_pull = object()  # probe ran
        w._next_probe = 8 * 4
        assert w._epoch_window_len(0, 12) == 8
        w._next_probe = 5  # drift refresh near: window must not cross it
        assert w._epoch_window_len(0, 12) == 5
        # resume: the horizon is relative to starting_epoch
        w = worker(4, starting_epoch=3)
        assert w._epoch_window_len(3, 12) == 1  # first probe still due
        # remaining epochs bound the window
        assert worker(0)._epoch_window_len(10, 12) == 2
        # non-deferrable epoch callback (checkpoint chains) disables windows
        w = worker(0, epoch_callback=lambda e: None)
        assert w._epoch_window_len(0, 12) == 1
        # deferrable (metrics-only) callback keeps them
        w = worker(0, epoch_callback=lambda e: None, defer_epoch_callback=True)
        assert w._epoch_window_len(0, 12) == 8
        # a trainer whose hook reads trained state opts out
        trainer.epoch_hook_windowable = False
        try:
            assert worker(0)._epoch_window_len(0, 12) == 1
        finally:
            del trainer.epoch_hook_windowable
        # a subclass overriding the hook WITHOUT opting in is excluded
        # even though its PARENT opted in — the flag describes the
        # parent's hook, not the override
        class PeekingMLR(MLRTrainer):
            def on_epoch_finished(self, ctx, epoch_idx):
                pass  # pretend it reads trained state

        trainer_peek = PeekingMLR(num_classes=2, num_features=8,
                                  features_per_partition=4)
        params = TrainerParams(num_epochs=12, num_mini_batches=4,
                               comm_probe_period=0)
        ctx = TrainerContext(params=params, model_table=table)
        w = WorkerTasklet("j2", ctx, trainer_peek,
                          TrainingDataProvider([x, y], 4), mesh8)
        assert w._epoch_window_len(0, 12) == 1


class TestCommProbe:
    def test_probe_feeds_pull_push_split(self, mesh8):
        """The per-epoch comm probe (WorkerTasklet._probe_comm) must emit a
        REAL pull/push split in BatchMetrics — not zeros — so the
        elasticity optimizer's comm_unit is measured, not degenerate (ref:
        ModelAccessor.java:33-49 pull/push timers feeding the optimizer)."""
        from harmony_tpu.metrics import MetricCollector, MetricManager

        manager = MetricManager()
        manager.start_collection()
        x, y = make_synthetic(128, num_features=16, num_classes=2)
        trainer = MLRTrainer(num_classes=2, num_features=16,
                             features_per_partition=4)
        params = TrainerParams(num_epochs=2, num_mini_batches=4)
        table = DenseTable(TableSpec(trainer.model_table_config()), mesh8)
        ctx = TrainerContext(params=params, model_table=table)
        w = WorkerTasklet(
            "probe-j", ctx, trainer, TrainingDataProvider([x, y], 4), mesh8,
            collector=MetricCollector(sink=manager.on_metric),
        )
        w.run()
        batches = manager.worker_batch_metrics()
        assert batches
        for b in batches:
            # pull is the all-gather — always measurable; push can land at
            # the CPU timing noise floor (it's derived by subtraction), so
            # comm_unit = pull+push stays > 0 either way
            assert b.pull_time_sec > 0
            assert b.push_time_sec >= 0
            # the split actually subtracted comm out of the step time
            assert b.comp_time_sec < b.batch_time_sec
            assert abs((b.pull_time_sec + b.push_time_sec + b.comp_time_sec)
                       - max(b.batch_time_sec,
                             b.pull_time_sec + b.push_time_sec)) < 1e-6

    def test_probe_disabled_degenerates_to_comp(self, mesh8):
        from harmony_tpu.metrics import MetricCollector, MetricManager

        manager = MetricManager()
        manager.start_collection()
        x, y = make_synthetic(64, num_features=8, num_classes=2)
        trainer = MLRTrainer(num_classes=2, num_features=8,
                             features_per_partition=4)
        params = TrainerParams(num_epochs=1, num_mini_batches=2)
        table = DenseTable(TableSpec(trainer.model_table_config()), mesh8)
        ctx = TrainerContext(params=params, model_table=table)
        w = WorkerTasklet(
            "noprobe-j", ctx, trainer, TrainingDataProvider([x, y], 2), mesh8,
            collector=MetricCollector(sink=manager.on_metric),
        )
        w.comm_probe_every = 0
        w.run()
        for b in manager.worker_batch_metrics():
            assert b.pull_time_sec == 0 and b.push_time_sec == 0
            assert b.comp_time_sec == b.batch_time_sec


class TestAsyncBatchedDispatch:
    def test_empty_metrics_trainer(self, mesh8):
        """A trainer whose compute returns no metrics must not crash the
        async per-batch drain (regression: StopIteration on empty dict)."""

        class SilentTrainer(AddVectorTrainer):
            def compute(self, model, batch, hyper):
                delta, _ = super().compute(model, batch, hyper)
                return delta, {}

        n, keys, dim = 64, 8, 4
        trainer = SilentTrainer(num_keys=keys, vector_dim=dim, delta=1.0)
        params = TrainerParams(num_epochs=2, num_mini_batches=2)
        spec = TableSpec(trainer.model_table_config())
        table = DenseTable(spec, mesh8)
        ctx = TrainerContext(params=params, model_table=table)
        data = TrainingDataProvider(list(make_marks(n)), 2)
        # a barrier that never stops forces the per-batch async path
        w = WorkerTasklet(
            "j", ctx, trainer, data, mesh8, batch_barrier=lambda i: False
        )
        result = w.run()
        assert result["epochs_run"] == 2
        vals = np.asarray(table.pull_array())
        np.testing.assert_allclose(
            vals, np.full((keys, dim), trainer.expected_value(n * 2))
        )
