"""Master-side control tests: SSP gate, budget stop, lifecycle barrier.

Analogues of the reference's WorkerStateManagerTest and the
MiniBatchController behavior (SSP ClockSlack blocking + budget broadcast).
"""
import threading
import time

import numpy as np
import pytest

from harmony_tpu.dolphin.master import (
    BatchProgressTracker,
    MiniBatchController,
    WorkerStateManager,
)


class TestMiniBatchController:
    def test_slack_blocks_fast_worker(self):
        c = MiniBatchController(clock_slack=2, batches_per_worker=100)
        c.register_worker("fast")
        c.register_worker("slow")
        events = []

        def fast():
            for i in range(6):
                c.on_sync("fast", i)
                events.append(("fast", i, time.perf_counter()))

        t = threading.Thread(target=fast)
        t.start()
        time.sleep(0.2)
        # fast must be blocked at batch 3 (0 + slack 2 < 3).
        fast_batches = [e[1] for e in events if e[0] == "fast"]
        assert max(fast_batches) == 2, fast_batches
        for i in range(6):
            c.on_sync("slow", i)
        t.join(timeout=5)
        assert not t.is_alive()
        assert max(e[1] for e in events) == 5

    def test_slack_zero_is_bsp(self):
        c = MiniBatchController(clock_slack=0, batches_per_worker=10)
        c.register_worker("a")
        c.register_worker("b")
        done = []

        def run_a():
            c.on_sync("a", 0)
            c.on_sync("a", 1)  # must block until b syncs batch 1... 0
            done.append("a1")

        t = threading.Thread(target=run_a)
        t.start()
        time.sleep(0.1)
        assert done == []
        c.on_sync("b", 0)
        c.on_sync("b", 1)
        t.join(timeout=5)
        assert done == ["a1"]

    def test_budget_stop_broadcast(self):
        c = MiniBatchController(clock_slack=10, batches_per_worker=3)
        c.register_worker("a")
        c.register_worker("b")
        assert not c.on_sync("a", 0)
        assert not c.on_sync("a", 1)
        assert not c.on_sync("a", 2)
        assert c.on_sync("a", 3)          # budget hit -> stop
        assert c.on_sync("b", 1)          # other worker sees broadcast stop
        assert c.stopped

    def test_deregister_unblocks(self):
        c = MiniBatchController(clock_slack=0, batches_per_worker=100)
        c.register_worker("a")
        c.register_worker("dead")
        result = []

        def run_a():
            c.on_sync("a", 1)
            result.append("released")

        t = threading.Thread(target=run_a)
        t.start()
        time.sleep(0.1)
        assert result == []
        c.deregister_worker("dead")       # finished worker must not gate
        t.join(timeout=5)
        assert result == ["released"]

    def test_tracker_starting_epoch(self):
        tr = BatchProgressTracker(num_mini_batches_per_epoch=4)
        c = MiniBatchController(clock_slack=8, batches_per_worker=100, tracker=tr)
        for i in range(9):
            c.on_sync("w0", i)
        for i in range(6):
            c.on_sync("w1", i)
        assert tr.global_min_batch() == 5
        assert tr.starting_epoch() == 1   # min 5 // 4


class TestWorkerStateManager:
    def test_barrier_releases_when_all_arrive(self):
        m = WorkerStateManager(["w0", "w1"])
        order = []

        def worker(wid, delay):
            time.sleep(delay)
            assert m.await_barrier(wid, "INIT", timeout=5)
            order.append(wid)

        ts = [
            threading.Thread(target=worker, args=("w0", 0.0)),
            threading.Thread(target=worker, args=("w1", 0.15)),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=5)
        assert sorted(order) == ["w0", "w1"]

    def test_membership_shrink_releases(self):
        m = WorkerStateManager(["w0", "w1", "w2"])
        released = []

        def worker(wid):
            assert m.await_barrier(wid, "RUN", timeout=5)
            released.append(wid)

        ts = [threading.Thread(target=worker, args=(w,)) for w in ["w0", "w1"]]
        for t in ts:
            t.start()
        time.sleep(0.1)
        assert released == []
        m.update_workers(["w0", "w1"])    # w2 removed by reconfiguration
        for t in ts:
            t.join(timeout=5)
        assert sorted(released) == ["w0", "w1"]


class TestSSPTraining:
    def test_two_async_workers_exact_sums(self, mesh8):
        """Two async worker threads, each on half the data, sharing one model
        table under an SSP gate — the multi-worker analogue of the AddVector
        validator: no push lost, final value exact."""
        from harmony_tpu.apps.addvector import AddVectorTrainer, make_marks
        from harmony_tpu.config.params import TrainerParams
        from harmony_tpu.dolphin import TrainerContext, TrainingDataProvider, WorkerTasklet
        from harmony_tpu.table import DenseTable, TableSpec

        n_per_worker, epochs, nb = 64, 2, 4
        trainer = AddVectorTrainer(num_keys=8, vector_dim=2, delta=1.0)
        table = DenseTable(TableSpec(trainer.model_table_config()), mesh8)
        ctrl = MiniBatchController(clock_slack=1, batches_per_worker=epochs * nb)
        results = {}

        def run_worker(wid):
            params = TrainerParams(num_epochs=epochs, num_mini_batches=nb)
            ctx = TrainerContext(params=params, model_table=table, worker_id=wid)
            w = WorkerTasklet(
                "ssp-job",
                ctx,
                AddVectorTrainer(num_keys=8, vector_dim=2, delta=1.0),
                TrainingDataProvider(list(make_marks(n_per_worker)), nb),
                mesh8,
                batch_barrier=ctrl.make_barrier(wid),
            )
            results[wid] = w.run()
            ctrl.deregister_worker(wid)

        ts = [threading.Thread(target=run_worker, args=(f"w{i}",)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        vals = np.asarray(table.pull_array())
        # Both workers processed all their batches: 2 workers x 128 examples.
        np.testing.assert_allclose(vals, np.full((8, 2), 2 * n_per_worker * epochs))


class TestHeterogeneousLeases:
    """Per-request resource specs (ref: HeterogeneousEvalManager.java:40-70
    matching allocations to requested node names/sizes): DevicePool leases
    and ETMaster.add_executors accept device-kind / process-index specs and
    stay all-or-nothing."""

    def test_lease_matching_kind(self, devices):
        from harmony_tpu.parallel import DevicePool

        pool = DevicePool(devices[:4])
        got = pool.lease("het-a", 2, device_kind="cpu")  # matches this host
        assert len(got) == 2
        with pytest.raises(RuntimeError, match="kind='tpu'"):
            pool.lease("het-b", 1, device_kind="tpu")
        # the failed spec-request must not have consumed anything
        assert len(pool.lease("het-c", 2)) == 2

    def test_lease_matching_process(self, devices):
        from harmony_tpu.parallel import DevicePool

        pool = DevicePool(devices[:2])
        assert len(pool.lease("p0", 2, process_index=0)) == 2
        pool.release("p0")
        with pytest.raises(RuntimeError, match="process=3"):
            pool.lease("p3", 1, process_index=3)

    def test_add_executors_with_spec(self, devices):
        from harmony_tpu.config.params import ExecutorConfig
        from harmony_tpu.parallel import DevicePool
        from harmony_tpu.runtime.master import ETMaster

        m = ETMaster(DevicePool(devices[:3]))
        ex = m.add_executors(2, ExecutorConfig(device_kind="cpu",
                                               process_index=0))
        assert len(ex) == 2
        # all-or-nothing with rollback: asking for 2 more cpu devices when
        # only 1 remains must grant none and release the partial lease
        before = set(m.executor_ids())
        with pytest.raises(RuntimeError, match="cannot allocate"):
            m.add_executors(2, ExecutorConfig(device_kind="cpu"))
        assert set(m.executor_ids()) == before
        assert len(m.add_executors(1)) == 1  # the rolled-back device is free
