"""Importable helpers for jobserver tests (resolve_symbol needs real
module-level symbols, mirroring how users ship trainer classes)."""
from __future__ import annotations

from harmony_tpu.apps.addvector import AddIntegerTrainer, AddVectorTrainer


class CrashOnW0Trainer(AddVectorTrainer):
    """Fails during init on worker w0 only — exercises uneven worker death
    (the surviving workers must not deadlock in the TaskUnit quorum)."""

    def init_global_settings(self, ctx) -> None:
        if ctx.worker_id.endswith("/w0"):
            raise RuntimeError("synthetic failure on w0")


def slow_data(n: int = 32):
    """Blocks long enough to wedge a job past any test shutdown timeout."""
    import time

    import numpy as np

    time.sleep(15)
    return (np.ones(n, np.float32),)


class ExplodingTrainer(AddIntegerTrainer):
    """Dies during global init on EVERY worker — the §5.3 failure-injection
    stand-in for multi-tenant isolation tests."""

    def init_global_settings(self, ctx) -> None:
        raise RuntimeError("injected failure")


class LaggyMLRTrainer:
    """MLR with a host-side per-epoch sleep on ONE worker — the straggler
    for SSP gating tests (the sleep is pure host delay: identical on every
    pod process, no device dispatch)."""

    def __new__(cls, lag_sec: float = 0.0, lag_worker: str = "/w1", **kw):
        from harmony_tpu.apps.mlr import MLRTrainer

        class _Laggy(MLRTrainer):
            def on_epoch_finished(self, ctx, epoch) -> None:
                import time

                if lag_sec and ctx.worker_id.endswith(lag_worker):
                    time.sleep(lag_sec)
                super().on_epoch_finished(ctx, epoch)

        return _Laggy(**kw)


class MoveOncePodOptimizer:
    """Optimizer SPI impl that emits ONE move-only plan (drain half of
    executor-4 onto executor-0) as soon as worker metrics exist — the
    canned optimizer for pod elasticity tests (the SampleOptimizers
    analogue for the pod plan channel)."""

    def __init__(self) -> None:
        self.fired = False

    def optimize(self, params, num_available_evaluators):
        from harmony_tpu.optimizer.api import DolphinPlan, TransferStep

        if self.fired or not params.worker_metrics:
            return DolphinPlan()
        src = "executor-4"
        n = params.block_counts.get(src, 0)
        if not n:
            return DolphinPlan()
        self.fired = True
        return DolphinPlan(transfer_steps=[
            TransferStep(params.table_id, src, "executor-0", n)
        ])
