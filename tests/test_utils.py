"""DAG + StateMachine unit tests (ref test analogues: DAGImplTest, state
machine usage in WorkerStateManagerTest)."""
import pytest

from harmony_tpu.utils import DAG, CyclicDependencyError, IllegalTransitionError, StateMachine


class TestDAG:
    def test_ready_and_release(self):
        d = DAG()
        for v in "abcd":
            d.add_vertex(v)
        d.add_edge("a", "b")
        d.add_edge("a", "c")
        d.add_edge("b", "d")
        d.add_edge("c", "d")
        assert d.roots() == ["a"]
        released = d.remove("a")
        assert sorted(released) == ["b", "c"]
        assert sorted(d.roots()) == ["b", "c"]
        assert d.remove("b") == []  # d still blocked by c
        assert d.remove("c") == ["d"]

    def test_cycle_rejected(self):
        d = DAG()
        d.add_vertex(1)
        d.add_vertex(2)
        d.add_edge(1, 2)
        with pytest.raises(CyclicDependencyError):
            d.add_edge(2, 1)

    def test_topological_order(self):
        d = DAG()
        for v in range(5):
            d.add_vertex(v)
        d.add_edge(0, 2)
        d.add_edge(1, 2)
        d.add_edge(2, 3)
        d.add_edge(2, 4)
        order = d.topological_order()
        assert order.index(2) > order.index(0)
        assert order.index(2) > order.index(1)
        assert order.index(3) > order.index(2)
        assert len(order) == 5


class TestStateMachine:
    def make(self):
        return StateMachine(
            states=["INIT", "RUN", "CLEANUP"],
            transitions=[("INIT", "RUN"), ("RUN", "CLEANUP")],
            initial="INIT",
        )

    def test_transitions(self):
        sm = self.make()
        assert sm.state == "INIT"
        sm.transition("RUN")
        assert sm.is_state("RUN")
        with pytest.raises(IllegalTransitionError):
            sm.transition("INIT")

    def test_compare_and_transition(self):
        sm = self.make()
        assert not sm.compare_and_transition("RUN", "CLEANUP")
        assert sm.compare_and_transition("INIT", "RUN")

    def test_wait_for(self):
        import threading

        sm = self.make()
        t = threading.Timer(0.05, lambda: sm.transition("RUN"))
        t.start()
        assert sm.wait_for("RUN", timeout=2.0)


class TestMultihost:
    """Single-host degradation paths of the multi-host wiring (a real
    multi-process run needs a pod; these pin the no-op semantics)."""

    def test_initialize_noop_single_host(self, monkeypatch):
        from harmony_tpu.parallel import multihost

        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert multihost.initialize_distributed() is False
        assert multihost.is_multihost() is False
        assert multihost.process_index() == 0
        assert multihost.process_count() == 1

    def test_global_mesh_spans_devices(self, devices):
        from harmony_tpu.parallel import multihost

        mesh = multihost.global_mesh(data=2, model=4)
        assert mesh.shape == {"data": 2, "model": 4}

    def test_sync_barrier_single_host(self):
        from harmony_tpu.parallel import multihost

        multihost.sync_global_devices("test")  # must not hang or raise

    def test_half_configured_launch_raises(self, monkeypatch):
        from harmony_tpu.parallel import multihost

        monkeypatch.setattr(multihost, "_initialized", False)
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
        monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
        with pytest.raises(ValueError, match="incomplete multi-host config"):
            multihost.initialize_distributed()
        monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
        monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="JAX_PROCESS_ID"):
            multihost.initialize_distributed()


class TestPlatformDetection:
    """TPU gates must recognize TPU chips exposed through experimental
    PJRT plugins (platform name != "tpu" but device_kind names the chip) —
    otherwise the Pallas/MXU fast paths silently fall back on hardware."""

    class _Dev:
        def __init__(self, platform, kind):
            self.platform = platform
            self.device_kind = kind

    def test_device_is_tpu(self):
        from harmony_tpu.utils.platform import device_is_tpu

        assert device_is_tpu(self._Dev("tpu", "TPU v4"))
        assert device_is_tpu(self._Dev("axon", "whatever"))
        assert device_is_tpu(self._Dev("plugin", "TPU v5 lite"))
        assert not device_is_tpu(self._Dev("cpu", "cpu"))

    def test_peak_bf16_flops(self):
        from harmony_tpu.utils.platform import peak_bf16_flops

        assert peak_bf16_flops(self._Dev("tpu", "TPU v5 lite")) == 197e12
        assert peak_bf16_flops(self._Dev("tpu", "TPU v4")) == 275e12
        assert peak_bf16_flops(self._Dev("cpu", "cpu")) in (None, 197e12)

    def test_tpu_backend_false_on_cpu(self):
        from harmony_tpu.utils.platform import tpu_backend

        assert tpu_backend() is False  # conftest pins the cpu backend
