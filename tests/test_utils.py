"""DAG + StateMachine unit tests (ref test analogues: DAGImplTest, state
machine usage in WorkerStateManagerTest)."""
import pytest

from harmony_tpu.utils import DAG, CyclicDependencyError, IllegalTransitionError, StateMachine


class TestDAG:
    def test_ready_and_release(self):
        d = DAG()
        for v in "abcd":
            d.add_vertex(v)
        d.add_edge("a", "b")
        d.add_edge("a", "c")
        d.add_edge("b", "d")
        d.add_edge("c", "d")
        assert d.roots() == ["a"]
        released = d.remove("a")
        assert sorted(released) == ["b", "c"]
        assert sorted(d.roots()) == ["b", "c"]
        assert d.remove("b") == []  # d still blocked by c
        assert d.remove("c") == ["d"]

    def test_cycle_rejected(self):
        d = DAG()
        d.add_vertex(1)
        d.add_vertex(2)
        d.add_edge(1, 2)
        with pytest.raises(CyclicDependencyError):
            d.add_edge(2, 1)

    def test_topological_order(self):
        d = DAG()
        for v in range(5):
            d.add_vertex(v)
        d.add_edge(0, 2)
        d.add_edge(1, 2)
        d.add_edge(2, 3)
        d.add_edge(2, 4)
        order = d.topological_order()
        assert order.index(2) > order.index(0)
        assert order.index(2) > order.index(1)
        assert order.index(3) > order.index(2)
        assert len(order) == 5


class TestStateMachine:
    def make(self):
        return StateMachine(
            states=["INIT", "RUN", "CLEANUP"],
            transitions=[("INIT", "RUN"), ("RUN", "CLEANUP")],
            initial="INIT",
        )

    def test_transitions(self):
        sm = self.make()
        assert sm.state == "INIT"
        sm.transition("RUN")
        assert sm.is_state("RUN")
        with pytest.raises(IllegalTransitionError):
            sm.transition("INIT")

    def test_compare_and_transition(self):
        sm = self.make()
        assert not sm.compare_and_transition("RUN", "CLEANUP")
        assert sm.compare_and_transition("INIT", "RUN")

    def test_wait_for(self):
        import threading

        sm = self.make()
        t = threading.Timer(0.05, lambda: sm.transition("RUN"))
        t.start()
        assert sm.wait_for("RUN", timeout=2.0)


class TestMultihost:
    """Single-host degradation paths of the multi-host wiring (a real
    multi-process run needs a pod; these pin the no-op semantics)."""

    def test_initialize_noop_single_host(self, monkeypatch):
        from harmony_tpu.parallel import multihost

        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert multihost.initialize_distributed() is False
        assert multihost.is_multihost() is False
        assert multihost.process_index() == 0
        assert multihost.process_count() == 1

    def test_global_mesh_spans_devices(self, devices):
        from harmony_tpu.parallel import multihost

        mesh = multihost.global_mesh(data=2, model=4)
        assert mesh.shape == {"data": 2, "model": 4}

    def test_sync_barrier_single_host(self):
        from harmony_tpu.parallel import multihost

        multihost.sync_global_devices("test")  # must not hang or raise

    def test_half_configured_launch_raises(self, monkeypatch):
        from harmony_tpu.parallel import multihost

        monkeypatch.setattr(multihost, "_initialized", False)
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
        monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
        with pytest.raises(ValueError, match="incomplete multi-host config"):
            multihost.initialize_distributed()
        monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
        monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="JAX_PROCESS_ID"):
            multihost.initialize_distributed()


class TestPlatformDetection:
    """TPU gates must recognize TPU chips exposed through experimental
    PJRT plugins (platform name != "tpu" but device_kind names the chip) —
    otherwise the Pallas/MXU fast paths silently fall back on hardware."""

    class _Dev:
        def __init__(self, platform, kind):
            self.platform = platform
            self.device_kind = kind

    def test_device_is_tpu(self):
        from harmony_tpu.utils.platform import device_is_tpu

        assert device_is_tpu(self._Dev("tpu", "TPU v4"))
        assert device_is_tpu(self._Dev("axon", "whatever"))
        assert device_is_tpu(self._Dev("plugin", "TPU v5 lite"))
        assert not device_is_tpu(self._Dev("cpu", "cpu"))

    def test_peak_bf16_flops(self):
        from harmony_tpu.utils.platform import peak_bf16_flops

        assert peak_bf16_flops(self._Dev("tpu", "TPU v5 lite")) == 197e12
        assert peak_bf16_flops(self._Dev("tpu", "TPU v4")) == 275e12
        assert peak_bf16_flops(self._Dev("cpu", "cpu")) in (None, 197e12)

    def test_tpu_backend_false_on_cpu(self):
        from harmony_tpu.utils.platform import tpu_backend

        assert tpu_backend() is False  # conftest pins the cpu backend


class TestHardSync:
    """hard_sync is the sync primitive every timing/backpressure site
    relies on: exactly block_until_ready on honest backends, and a
    device-side scalar read on lazy-dispatch backends (the axon remote
    client acks block_until_ready without executing)."""

    def test_not_lazy_on_cpu(self):
        from harmony_tpu.utils import platform as plat

        plat._LAZY_CACHE = None  # force re-detection
        assert plat.lazy_dispatch_backend() is False

    def test_returns_input_identity(self):
        import jax.numpy as jnp

        from harmony_tpu.utils.platform import hard_sync

        x = {"a": jnp.ones((3,)), "b": (jnp.arange(2), None)}
        assert hard_sync(x) is x

    def test_forced_lazy_reads_all_leaf_kinds(self, monkeypatch):
        """With the lazy path forced, the read must survive floats, ints,
        bools, typed PRNG keys (no astype), empty leaves, and non-array
        entries."""
        import jax
        import jax.numpy as jnp

        from harmony_tpu.utils import platform as plat

        monkeypatch.setattr(plat, "_LAZY_CACHE", True)
        out = {
            "f": jnp.ones((4, 2)),
            "i": jnp.arange(3),
            "b": jnp.array([True, False]),
            "key": jax.random.key(7),
            "empty": jnp.zeros((0,)),
            "none": None,
            "scalar": 3.5,
        }
        assert plat.hard_sync(out) is out
        assert plat.hard_sync(jax.random.key(0)) is not None
        assert plat.hard_sync({}) == {}

    def test_forced_lazy_fallback_reads_each_leaf(self, monkeypatch):
        """The cross-device ValueError fallback must read every leaf
        separately. The fused-sum path can't fail on a CPU mesh, so the
        failure is injected: the FIRST ravel raises (standing in for the
        cross-device `acc + v`), and the per-leaf fallback must then
        ravel each of the leaves."""
        import jax
        import jax.numpy as jnp

        from harmony_tpu.utils import platform as plat

        monkeypatch.setattr(plat, "_LAZY_CACHE", True)
        calls = {"n": 0}
        real_ravel = jnp.ravel

        def flaky_ravel(x):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("incompatible devices (injected)")
            return real_ravel(x)

        monkeypatch.setattr(jax.numpy, "ravel", flaky_ravel)
        out = {"a": jnp.ones((3,)), "b": jnp.arange(4)}
        assert plat.hard_sync(out) is out
        # 1 aborted fused attempt + one ravel per leaf in the fallback
        assert calls["n"] == 1 + len(out)

    def test_forced_lazy_multi_device_leaves_enter_dispatch_scope(
        self, monkeypatch, devices
    ):
        """Sharded leaves must route the reads through the process-wide
        dispatch scope — asserted via a spy, not assumed."""
        import contextlib

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from harmony_tpu.parallel import build_mesh, dispatch
        from harmony_tpu.utils import platform as plat

        monkeypatch.setattr(plat, "_LAZY_CACHE", True)
        entered = []

        @contextlib.contextmanager
        def spy_scope(mesh):
            entered.append(mesh)
            yield lambda x: x

        monkeypatch.setattr(dispatch, "dispatch_scope", spy_scope)
        mesh = build_mesh(devices, data=len(devices))
        x = jax.device_put(jnp.ones((16, 4)), NamedSharding(mesh, P("data")))
        plat.hard_sync({"x": x, "y": jnp.ones((2,))})
        assert entered == [mesh]
        # single-device leaves skip the scope entirely
        entered.clear()
        plat.hard_sync(jnp.ones((4,)))
        assert entered == []


class TestEnvChoice:
    """Operator rollback knobs must warn (once) on unrecognized values
    instead of silently staying on the default."""

    def test_valid_and_missing(self, monkeypatch):
        from harmony_tpu.utils.platform import env_choice

        monkeypatch.delenv("X_KNOB", raising=False)
        assert env_choice("X_KNOB", ("a", "b")) is None
        monkeypatch.setenv("X_KNOB", "b")
        assert env_choice("X_KNOB", ("a", "b")) == "b"

    def test_invalid_warns_once_and_ignores(self, monkeypatch, caplog):
        import logging

        from harmony_tpu.utils import platform as plat

        monkeypatch.setattr(plat, "_WARNED_ENV", set())
        monkeypatch.setenv("Y_KNOB", "Bogus")
        with caplog.at_level(logging.WARNING):
            assert plat.env_choice("Y_KNOB", ("a", "b")) is None
            assert plat.env_choice("Y_KNOB", ("a", "b")) is None
        warns = [r for r in caplog.records if "Y_KNOB" in r.getMessage()]
        assert len(warns) == 1  # once, not per call
