"""CLI + jobserver entity coverage: presets build valid configs, every app
preset runs standalone on the virtual mesh, pregel jobs flow through the
jobserver (PregelJobEntity), and submissions survive the TCP control plane.
"""
import json

import numpy as np
import pytest

from harmony_tpu.cli import PRESETS, build_config, main


class _Args:
    """Minimal argparse.Namespace stand-in for build_config."""

    def __init__(self, **kw):
        self.job_id = None
        self.epochs = 2
        self.batches = 2
        self.workers = 2
        self.slack = 0
        self.set = []
        self.data = []
        self.graph_file = None
        self.max_supersteps = 20
        self.optimizer = None
        self.optimizer_period = 5.0
        self.model_chkp_period = 0
        self.offline_eval = False
        self.__dict__.update(kw)


@pytest.mark.parametrize("app", sorted(PRESETS))
def test_presets_build_and_serialize(app):
    cfg = build_config(app, _Args())
    # must survive the TCP control plane's JSON framing
    blob = json.dumps(cfg.to_dict())
    assert cfg.job_id == f"{app}-job"
    assert json.loads(blob)["app_type"] in ("dolphin", "pregel")


def test_overrides_applied():
    cfg = build_config("mlr", _Args(
        set=["num_classes=5"], data=["n=512", "num_classes=5"], epochs=7))
    assert cfg.params.app_params["num_classes"] == 5
    assert cfg.user["data_args"]["n"] == 512
    assert cfg.params.num_epochs == 7


def test_unknown_app_exits():
    with pytest.raises(SystemExit):
        build_config("nope", _Args())


def test_bad_override_exits():
    with pytest.raises(SystemExit):
        build_config("mlr", _Args(set=["oops"]))


@pytest.mark.parametrize("app", ["addinteger", "mlr", "pagerank", "lm"])
def test_cli_run_standalone(app, capsys):
    """`harmony-tpu run <app>` end-to-end on the virtual mesh (tiny scales)."""
    args = ["run", app, "--epochs", "1", "--batches", "2", "--workers", "2",
            "--num-executors", "4", "--max-supersteps", "5"]
    if app == "mlr":
        args += ["--data", "n=256"]
    if app == "lm":
        args += ["--data", "num_seqs=16"]
    rc = main(args)
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["job_id"] == f"{app}-job"


def test_pregel_entity_through_jobserver(devices):
    """PregelJobEntity: pagerank submitted to an in-process JobServer
    produces a normalized rank distribution."""
    from harmony_tpu.jobserver.server import JobServer

    server = JobServer(num_executors=4)
    server.start()
    try:
        cfg = build_config("pagerank", _Args(
            data=["num_vertices=200", "avg_degree=4"], max_supersteps=12))
        result = server.submit(cfg).result(timeout=300)
        assert result["supersteps"] >= 1
        # vertex table already dropped at cleanup; result carries the state
        # [rank, out_degree] per vertex — ranks are a distribution.
        state = np.asarray(result["vertex_values"])
        assert state.shape[0] == 200
        np.testing.assert_allclose(state[:, 0].sum(), 1.0, atol=1e-2)
    finally:
        server.shutdown(timeout=60)


def test_submit_over_tcp(devices):
    """submit/status/shutdown through the real TCP control plane."""
    from harmony_tpu.jobserver.client import CommandSender
    from harmony_tpu.jobserver.server import JobServer

    server = JobServer(num_executors=2)
    server.start()
    port = server.serve_tcp(0)
    try:
        sender = CommandSender(port)
        resp = sender.send_job_submit_command(
            build_config("addinteger", _Args(workers=2)))
        assert resp.get("ok"), resp
        assert sender.send_status_command().get("ok")
    finally:
        CommandSender(port).send_shutdown_command()


@pytest.mark.parametrize("app", sorted(PRESETS))
def test_preset_symbols_bind(app):
    """Every preset's trainer and data/graph builder must resolve AND their
    preset kwargs must bind against the real signatures — catches key drift
    (e.g. doc_len vs max_doc_len) without running jax."""
    import inspect

    from harmony_tpu.config.base import resolve_symbol

    cfg = build_config(app, _Args())
    trainer_cls = resolve_symbol(cfg.trainer)
    sig = inspect.signature(trainer_cls.__init__)
    app_params = dict(cfg.params.app_params)
    if cfg.app_type == "pregel" and "graph" in sig.parameters:
        app_params["graph"] = None
    sig.bind(None, **app_params)  # raises TypeError on drift
    if cfg.app_type == "pregel":
        fn = resolve_symbol(cfg.user["graph_fn"])
        inspect.signature(fn).bind(**cfg.user["graph_args"])
    else:
        fn = resolve_symbol(cfg.user["data_fn"])
        inspect.signature(fn).bind(**cfg.user["data_args"])


def test_start_pod_requires_topology(monkeypatch, capsys):
    """start-pod must refuse a half-configured launch (missing coordinator/
    process id) instead of silently running single-host while peers block in
    jax.distributed.initialize."""
    from harmony_tpu.cli import main

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert main(["start-pod"]) == 2
    assert "start-pod needs" in capsys.readouterr().err


def test_cli_flags_reach_job_config():
    """--optimizer/--model-chkp-period/--offline-eval plumb into JobConfig."""
    from harmony_tpu.cli import build_config

    args = _Args(epochs=2, batches=2, workers=1)
    args.optimizer = "homogeneous"
    args.optimizer_period = 1.5
    args.model_chkp_period = 2
    args.offline_eval = True
    cfg = build_config("mlr", args)
    assert cfg.optimizer == "homogeneous"
    assert cfg.optimizer_period == 1.5
    assert cfg.params.model_chkp_period == 2
    assert cfg.params.offline_model_eval is True
    # pod knobs: --auto-resume / --pod-isolated land in user{}
    d = _Args(epochs=2, batches=2, workers=1)
    d.model_chkp_period = 1
    d.auto_resume = True
    d.pod_isolated = True
    cfg = build_config("mlr", d)
    assert cfg.user["auto_resume"] is True
    assert cfg.user["pod_isolated"] is True


def test_cli_rejects_misconfigured_flags():
    from harmony_tpu.cli import build_config
    import pytest

    a = _Args()
    a.offline_eval = True  # no chkp chain to replay
    with pytest.raises(SystemExit, match="model-chkp-period"):
        build_config("mlr", a)
    b = _Args()
    b.optimizer = "homogenous"  # typo: fails at submit, not mid-job
    with pytest.raises(SystemExit, match="unknown --optimizer"):
        build_config("mlr", b)
    c = _Args()
    c.optimizer = "homogeneous"  # dolphin-only flag on a graph app
    with pytest.raises(SystemExit, match="dolphin"):
        build_config("pagerank", c)
    d = _Args()
    d.auto_resume = True  # no chain to restore from
    with pytest.raises(SystemExit, match="model-chkp-period"):
        build_config("mlr", d)


def test_lm_preset_with_file_corpus(tmp_path):
    """`--data path=...` on the lm preset swaps in the byte-level file
    loader while the coupled vocab sync still applies."""
    p = tmp_path / "c.txt"
    p.write_text("x" * 10000)
    cfg = build_config("lm", _Args(data=[f"path={p}"]))
    assert cfg.user["data_fn"].endswith(":load_text_tokens")
    assert cfg.user["data_args"]["path"] == str(p)
    # coupled key still synced between model and data sides
    assert (cfg.params.app_params["vocab_size"]
            == cfg.user["data_args"]["vocab_size"])


def test_lm_file_corpus_rejects_stray_data_keys(tmp_path):
    p = tmp_path / "c.txt"
    p.write_text("y" * 1000)
    with pytest.raises(SystemExit, match="do not apply to file corpora"):
        build_config("lm", _Args(data=[f"path={p}", "seed=3"]))


def test_file_corpus_keys_pin_real_signature():
    """FILE_CORPUS_KEYS is static (the submit path must stay jax-free) —
    this test is what keeps it in sync with load_text_tokens."""
    import inspect

    from harmony_tpu.cli import FILE_CORPUS_KEYS
    from harmony_tpu.models.transformer import load_text_tokens

    assert FILE_CORPUS_KEYS == frozenset(
        inspect.signature(load_text_tokens).parameters)


# -- obs output contracts ---------------------------------------------------

#: one canned STATUS reply serving every obs subcommand, with an open
#: incident whose latencies are still unknown (the '-' contract)
_OBS_STATUS = {
    "ok": True,
    "tenants": {"t0": {"device_time_ms": 12.5,
                       "serving": {"enabled": True, "qps": 120.4,
                                   "p50_ms": None, "p99_ms": 4.9,
                                   "slo_p99_ms": 50.0,
                                   "batch_occupancy": None,
                                   "cache_hit_rate": None}}},
    "overload": {},
    "diagnoses": [{"tenant": "t0", "verdict": "input_bound"}],
    "history": {"epochs": 3},
    "phase_budget": {"t0": {"compute_ms": 9.0}},
    "policy": {"decisions": []},
    "incidents": {
        "open": 1, "mitigating": 0, "resolved": 0, "adopted": 0,
        "window_sec": 120.0, "mttr_mean_sec": None,
        "incidents": [{
            "incident_id": "t0:slo:1", "subject": "t0", "status": "open",
            "trigger_kind": "slo", "opened_ts": 100.0, "last_ts": 100.5,
            "mttd_sec": None, "mitigate_sec": None, "mttr_sec": None,
            "verdict": None,
            "chain": [
                {"role": "trigger", "kind": "slo", "src": "joblog",
                 "ts": 100.0, "summary": "slo"},
                {"role": "diagnosis", "kind": "diagnosis", "src": "joblog",
                 "ts": 100.5, "summary": "diagnosis verdict=input_bound",
                 "verdict": "input_bound"},
            ],
        }],
    },
    "flight_records": [],
    "stragglers": {},
    "metrics_port": None,
    "profile_capture": None,
}


class _FakeObsSender:
    def __init__(self, reply):
        self._reply = reply

    def send_status_command(self):
        reply = self._reply
        if isinstance(reply, BaseException):
            raise reply
        return reply


#: what `--json` must emit per subcommand: the named STATUS section(s),
#: verbatim — scripts parse this shape
_OBS_JSON_CONTRACT = {
    "top": lambda s: s["tenants"],
    "doctor": lambda s: {"diagnoses": s["diagnoses"],
                         "history": s["history"]},
    "critpath": lambda s: s["phase_budget"],
    "plan": lambda s: s["policy"],
    "incidents": lambda s: s["incidents"],
}


@pytest.mark.parametrize("what", sorted(_OBS_JSON_CONTRACT))
def test_obs_json_contract(what, monkeypatch, capsys):
    """Every STATUS-backed obs subcommand honors --json with the raw
    section of the canned STATUS, parseable and verbatim."""
    from harmony_tpu import cli

    monkeypatch.setattr(cli, "_obs_status_sender",
                        lambda kind, ep: _FakeObsSender(_OBS_STATUS))
    rc = main(["obs", what, "--port", "1", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    assert json.loads(out) == _OBS_JSON_CONTRACT[what](_OBS_STATUS)


@pytest.mark.parametrize("what", sorted(_OBS_JSON_CONTRACT))
def test_obs_not_ok_status_is_one_json_line(what, monkeypatch, capsys):
    from harmony_tpu import cli

    refusal = {"ok": False, "error": "no leader"}
    monkeypatch.setattr(cli, "_obs_status_sender",
                        lambda kind, ep: _FakeObsSender(refusal))
    rc = main(["obs", what, "--port", "1"])
    assert rc == 1
    assert json.loads(capsys.readouterr().out) == refusal


def test_obs_top_serving_row_renders_unknowns_as_dash(monkeypatch,
                                                      capsys):
    """A serving tenant gets a latency line under the table; quantities
    the endpoint hasn't measured yet render '-', never a fake 0."""
    from harmony_tpu import cli

    monkeypatch.setattr(cli, "_obs_status_sender",
                        lambda kind, ep: _FakeObsSender(_OBS_STATUS))
    rc = main(["obs", "top", "--port", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "serving t0:" in out
    assert "qps 120.4" in out and "p99 4.9ms" in out
    assert "(slo 50ms)" in out
    assert "p50 -" in out and "occupancy -" in out and "cache hit -" in out
    assert "p50 0" not in out and "cache hit 0" not in out


def test_obs_incidents_renders_unknowns_as_dash(monkeypatch, capsys):
    """An open incident has no MTTR/MTTD yet: the human view must say
    '-', never 0 (a zero latency would be a lie)."""
    from harmony_tpu import cli

    monkeypatch.setattr(cli, "_obs_status_sender",
                        lambda kind, ep: _FakeObsSender(_OBS_STATUS))
    rc = main(["obs", "incidents", "--port", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "mttd=- mitigate=- mttr=-" in out
    assert "mean_mttr=-" in out
    assert "mttr=0.000" not in out
    # the causal chain renders as a timeline, diagnosis under trigger
    assert "trigger" in out and "verdict=input_bound" in out


@pytest.mark.parametrize("what",
                         sorted(_OBS_JSON_CONTRACT) + ["flight"])
def test_obs_survives_broken_pipe(what, monkeypatch, capfd):
    """obs output is made for `| head`: a closed pipe ends the command
    quietly (exit 0), never a stack trace."""
    import os
    import sys

    from harmony_tpu import cli

    monkeypatch.setattr(
        cli, "_obs_status_sender",
        lambda kind, ep: _FakeObsSender(BrokenPipeError()))
    # the handler points sys.stdout's REAL fd at /dev/null (that's the
    # point); save and restore it so the test runner keeps its stdout
    fd = sys.stdout.fileno()
    saved = os.dup(fd)
    try:
        rc = main(["obs", what, "--port", "1"])
    finally:
        os.dup2(saved, fd)
        os.close(saved)
    assert rc == 0
    assert "Traceback" not in capfd.readouterr().err
