"""Root-cause doctor tests (PR 11; docs/OBSERVABILITY.md §8): every
shipped rule fires on its synthetic scenario and stays silent on
healthy traces; diagnoses dedupe to once per (rule, subject) per
window; they land as structured joblog events and in flight dumps; and
the fault-injected acceptance drives four distinct scenarios through a
real JobServer + TCP STATUS + ``harmony-tpu obs doctor``."""
import json
import time

import pytest

from harmony_tpu.metrics.doctor import (
    Doctor,
    all_rules,
    peek_doctor,
    set_doctor,
)
from harmony_tpu.metrics.history import HistoryStore


def _store(window=600.0):
    return HistoryStore(window_sec=window, resolution_sec=0.01)


def _feed(store, name, labels, values, spacing=1.0, kind="gauge",
          target=None):
    t0 = time.time() - spacing * len(values)
    for i, v in enumerate(values):
        store.ingest(name, labels, v, ts=t0 + i * spacing, kind=kind,
                     target=target)


class TestRuleCatalog:
    def test_shipped_rules_present_in_order(self):
        names = [r.name for r in all_rules()]
        # slo_breach joins the others and must stay LAST (declaration
        # order is evaluation order); the PR-13 phase rules sit before it
        assert names == ["input_bound", "straggler", "mfu_collapse",
                         "compile_storm", "infra_suspect", "comm_bound",
                         "dispatch_bound", "leader_flap",
                         "rebalance_ineffective", "control_overload",
                         "serving_slo_breach", "slo_breach"]
        assert all(r.description for r in all_rules())

    def test_input_bound_fires_and_names_tenant(self):
        s = _store()
        _feed(s, "tenant.input_wait_frac", {"job": "slow-j"},
              [0.7, 0.8, 0.75])
        _feed(s, "tenant.input_wait_frac", {"job": "ok-j"},
              [0.05, 0.1, 0.02])
        out = Doctor(s, events_fn=dict).diagnose()
        assert [d.rule for d in out] == ["input_bound"]
        d = out[0]
        assert d.job == "slow-j"
        assert d.evidence["points"]  # non-empty evidence excerpt
        assert d.evidence["median"] == pytest.approx(0.75)

    def test_input_bound_silent_on_healthy_trace(self):
        s = _store()
        _feed(s, "tenant.input_wait_frac", {"job": "ok-j"},
              [0.1, 0.2, 0.15])
        assert Doctor(s, events_fn=dict).diagnose() == []

    def test_straggler_fires_with_worker_attribution(self):
        s = _store()
        _feed(s, "tenant.straggler_ratio", {"job": "lag-j"},
              [2.5, 3.0, 2.8])
        strag = {"lag-j": {"slowest": "w3",
                           "workers": {"w0": 0.1, "w3": 0.3},
                           "ratio": 2.8}}
        out = Doctor(s, events_fn=dict,
                     stragglers_fn=lambda: strag).diagnose()
        (d,) = out
        assert d.rule == "straggler" and d.job == "lag-j"
        assert d.evidence["slowest_worker"] == "w3"

    def test_straggler_silent_when_ratio_healthy(self):
        s = _store()
        _feed(s, "tenant.straggler_ratio", {"job": "j"}, [1.0, 1.1, 1.05])
        assert Doctor(s, events_fn=dict).diagnose() == []

    def test_mfu_collapse_needs_layout_change_correlation(self):
        s = _store()
        drop = [0.5, 0.5, 0.5, 0.1, 0.1, 0.1]
        _feed(s, "tenant.mfu", {"job": "m-j"}, drop)
        # no layout bump in window: the drop alone must NOT fire
        assert Doctor(s, events_fn=dict).diagnose() == []
        _feed(s, "harmony_table_layout_changes_total",
              {"target": "leader"}, [3.0, 4.0], kind="counter",
              target="leader")
        (d,) = Doctor(s, events_fn=dict).diagnose()
        assert d.rule == "mfu_collapse" and d.job == "m-j"
        assert d.evidence["layout_changes"] == 1.0
        assert d.evidence["late_mean"] < d.evidence["early_mean"]

    def test_mfu_collapse_silent_on_flat_mfu_despite_layout_change(self):
        s = _store()
        _feed(s, "tenant.mfu", {"job": "m-j"}, [0.5] * 6)
        _feed(s, "harmony_table_layout_changes_total",
              {"target": "leader"}, [3.0, 4.0], kind="counter")
        assert Doctor(s, events_fn=dict).diagnose() == []

    def test_compile_storm_fires_per_target_with_pid(self):
        s = _store(window=60.0)
        # 2 compile-seconds per wall second, all misses, on pod:2
        _feed(s, "harmony_compile_seconds_sum",
              {"target": "pod:2", "program": "step"},
              [0.0, 2.0, 4.0, 6.0], kind="counter", target="pod:2")
        _feed(s, "harmony_progcache_events_total",
              {"target": "pod:2", "result": "miss"},
              [0.0, 1.0, 2.0, 3.0], kind="counter", target="pod:2")
        with s._lock:  # pid attribution comes from target metadata
            s._target_meta["pod:2"] = {"pid": "4242", "start_time": None}
        (d,) = Doctor(s, window=60.0, events_fn=dict).diagnose()
        assert d.rule == "compile_storm"
        assert d.target == "pod:2" and d.pid == "4242"
        assert d.evidence["compile_seconds_rate"] >= 0.25

    def test_compile_storm_silent_when_cache_hits(self):
        s = _store(window=60.0)
        _feed(s, "harmony_compile_seconds_sum",
              {"target": "pod:2", "program": "step"},
              [0.0, 2.0, 4.0], kind="counter", target="pod:2")
        # no miss rate: warm cache, compiles are legitimate first-builds
        assert Doctor(s, window=60.0, events_fn=dict).diagnose() == []

    def test_infra_suspect_names_the_bursting_target(self):
        s = _store()
        _feed(s, "harmony_retry_events_total",
              {"target": "pod:1", "op": "blockmove.send",
               "kind": "retries"},
              [0.0, 3.0, 7.0], kind="counter", target="pod:1")
        _feed(s, "harmony_retry_events_total",
              {"target": "pod:3", "op": "blockmove.send",
               "kind": "retries"},
              [0.0, 0.0, 1.0], kind="counter", target="pod:3")
        (d,) = Doctor(s, events_fn=dict).diagnose()
        assert d.rule == "infra_suspect" and d.target == "pod:1"
        assert d.evidence["events_in_window"] == 7.0

    def test_infra_suspect_ignores_the_scrapers_own_retries(self):
        """The doctor must not diagnose itself: a dead scrape target
        produces obs.scrape retry events on the LEADER every cycle —
        already reported as gap marks — and counting them as an infra
        burst would blame the wrong process once per window forever."""
        s = _store()
        _feed(s, "harmony_retry_events_total",
              {"target": "leader", "op": "obs.scrape",
               "kind": "retries"},
              [0.0, 120.0, 360.0], kind="counter", target="leader")
        assert Doctor(s, events_fn=dict).diagnose() == []

    def test_slo_breach_joins_to_its_cause(self):
        from harmony_tpu.jobserver import joblog

        s = _store()
        _feed(s, "tenant.input_wait_frac", {"job": "slo-j"},
              [0.8, 0.9, 0.85])
        joblog.clear_events("slo-j")
        joblog.record_event("slo-j", "slo", attainment=0.4,
                            target_sps=100.0)
        try:
            out = Doctor(s).diagnose()
            rules = {d.rule: d for d in out}
            assert set(rules) == {"input_bound", "slo_breach"}
            b = rules["slo_breach"]
            assert b.job == "slo-j"
            assert b.evidence["cause_rule"] == "input_bound"
            assert b.confidence > 0.5
        finally:
            joblog.clear_events("slo-j")

    def test_slo_breach_without_cause_is_unattributed(self):
        from harmony_tpu.jobserver import joblog

        joblog.clear_events("lone-j")
        joblog.record_event("lone-j", "slo", attainment=0.5)
        try:
            (d,) = Doctor(_store()).diagnose()
            assert d.rule == "slo_breach"
            assert d.evidence["cause_rule"] is None
            assert "unattributed" in d.summary
        finally:
            joblog.clear_events("lone-j")


class TestEngineSemantics:
    def test_once_per_window_then_rearms(self):
        s = _store(window=30.0)
        _feed(s, "tenant.input_wait_frac", {"job": "j"}, [0.9, 0.9, 0.9])
        doc = Doctor(s, window=30.0, events_fn=dict)
        now = time.time()
        assert len(doc.diagnose(now=now)) == 1
        # same condition, same window: exactly once
        assert doc.diagnose(now=now + 1) == []
        assert doc.diagnose(now=now + 15) == []
        # the window has passed and the condition persists: re-diagnose
        # (points stamped inside the NEXT window, as live scrapes would)
        s.ingest("tenant.input_wait_frac", {"job": "j"}, 0.9,
                 ts=now + 30.2)
        s.ingest("tenant.input_wait_frac", {"job": "j"}, 0.9,
                 ts=now + 30.6)
        assert len(doc.diagnose(now=now + 31)) == 1
        assert len(doc.recent()) == 2
        # expired dedup entries are pruned, not leaked: only the fresh
        # emission's key survives the re-arm
        assert len(doc._seen) == 1

    def test_diagnosis_lands_as_joblog_event(self):
        from harmony_tpu.jobserver import joblog

        s = _store()
        _feed(s, "tenant.input_wait_frac", {"job": "ev-j"}, [0.9, 0.9])
        joblog.clear_events("ev-j")
        try:
            Doctor(s, events_fn=dict).diagnose()
            evs = [e for e in joblog.job_events("ev-j")
                   if e["kind"] == "diagnosis"]
            assert len(evs) == 1
            assert evs[0]["rule"] == "input_bound"
            assert evs[0]["verdict"] == "input_bound"
            assert evs[0]["evidence"]["points"]
            json.dumps(evs)  # rides STATUS verbatim
        finally:
            joblog.clear_events("ev-j")

    def test_sink_sees_fresh_diagnoses_and_cannot_break_engine(self):
        s = _store()
        _feed(s, "tenant.input_wait_frac", {"job": "j"}, [0.9, 0.9])
        seen = []

        def bad_sink(d):
            seen.append(d)
            raise RuntimeError("sink bug")

        out = Doctor(s, events_fn=dict, sinks=(bad_sink,)).diagnose()
        assert len(out) == 1 and seen == out

    def test_broken_rule_does_not_silence_the_rest(self, monkeypatch):
        from harmony_tpu.metrics import doctor as doc_mod

        s = _store()
        _feed(s, "tenant.input_wait_frac", {"job": "j"}, [0.9, 0.9])

        def boom(ctx):
            raise RuntimeError("rule bug")

        monkeypatch.setitem(
            doc_mod._RULES, "straggler",
            doc_mod.DoctorRule("straggler", "broken for test", boom))
        out = Doctor(s, events_fn=dict).diagnose()
        assert [d.rule for d in out] == ["input_bound"]

    def test_flight_dump_snapshots_diagnoses(self, tmp_path):
        from harmony_tpu.tracing.flight import FlightRecorder

        s = _store()
        _feed(s, "tenant.input_wait_frac", {"job": "fl-j"}, [0.9, 0.9])
        doc = Doctor(s, events_fn=dict)
        doc.diagnose()
        prev = peek_doctor()
        set_doctor(doc)
        try:
            rec = FlightRecorder(capacity=16, out_dir=str(tmp_path))
            path = rec.dump("test")
            body = json.load(open(path))
            assert body["diagnoses"]
            assert body["diagnoses"][-1]["rule"] == "input_bound"
        finally:
            set_doctor(prev)


class TestPodTargetDiscovery:
    def test_heartbeat_ports_become_scrape_targets(self, devices):
        """The leader's scraper discovers followers from the heartbeat
        plumbing: advertised metrics ports become HTTP targets keyed by
        pid; dead/silenced followers are skipped (their gap IS the
        signal); the ports ride STATUS for operators."""
        from harmony_tpu.jobserver.pod import PodJobServer
        from harmony_tpu.metrics.doctor import set_doctor

        srv = PodJobServer(num_executors=2, num_followers=0)
        try:
            with srv._pod_cond:
                srv._hb_metrics_ports[1] = 9464
                srv._follower_hosts[1] = "10.0.0.9"
                srv._hb_metrics_ports[2] = 9000  # dead: must be skipped
                srv._dead_followers.add(2)
                srv._hb_metrics_ports[3] = 9001  # no host seen yet
            targets = srv._scrape_targets()
            assert targets["pod:1"] == "http://10.0.0.9:9464/metrics"
            assert "pod:2" not in targets
            assert targets["pod:3"] == "http://127.0.0.1:9001/metrics"
            assert callable(targets["leader"])  # in-process, no HTTP
            ports = srv._status()["pod"]["metrics_ports"]
            assert ports == {"1": 9464, "2": 9000, "3": 9001}
        finally:
            set_doctor(None)

    def test_extra_env_targets_reach_the_provider(self, devices,
                                                  monkeypatch):
        from harmony_tpu.jobserver.server import JobServer
        from harmony_tpu.metrics.doctor import set_doctor
        from harmony_tpu.metrics.history import ENV_EXTRA_TARGETS

        monkeypatch.setenv(ENV_EXTRA_TARGETS, "inputsvc=10.1.2.3:9464")
        srv = JobServer(num_executors=1)
        try:
            t = srv._scrape_targets()
            assert t["inputsvc"] == "http://10.1.2.3:9464/metrics"
        finally:
            set_doctor(None)


@pytest.mark.faults
class TestAcceptance:
    """Fault-injected acceptance (ISSUE 11): four distinct injected
    scenarios — input stall, straggler, fault burst, SLO breach —
    must each yield the correct verdict with correct tenant/pid
    attribution and non-empty evidence, exactly once per window,
    through the REAL stack: jobserver scraper -> store -> doctor ->
    STATUS over TCP -> ``harmony-tpu obs doctor``."""

    def test_four_scenarios_end_to_end(self, devices, capsys,
                                       monkeypatch):
        from harmony_tpu import faults
        from harmony_tpu.config.params import RetryPolicy
        from harmony_tpu.faults.retry import RetryError, call_with_retry
        from harmony_tpu.jobserver import joblog
        from harmony_tpu.jobserver.server import JobServer
        from harmony_tpu.metrics.accounting import ledger, reset_ledger
        from harmony_tpu.metrics.collector import BatchMetrics
        from harmony_tpu.cli import main as cli_main

        reset_ledger()
        joblog.clear_events()
        faults.reset_counters()
        # fine-grained buckets so back-to-back polls in this test are
        # distinct points (prod default is 5s — scrape-period scale)
        monkeypatch.setenv("HARMONY_OBS_RESOLUTION", "0.01")
        server = JobServer(num_executors=2)
        # keep the background loop out of the way; we drive polls by hand
        server._history_scraper.period = 3600.0
        server.start()
        try:
            led = ledger()
            # scenario 1 — INPUT STALL on tenant stall-j: device seconds
            # dwarfed by injected prefetch consumer-stall seconds
            led.observe_steps("stall-j", "stall-j", "w0", steps=10,
                              device_sec=1.0, examples=100,
                              input_wait_sec=9.0)
            # scenario 2 — STRAGGLER on tenant lag-j: worker w1 runs 3x
            # slower than its peers
            led.observe_steps("lag-j", "lag-j", "w0", steps=10,
                              device_sec=1.0, examples=100)
            for w, dt in (("w0", 0.1), ("w1", 0.3), ("w2", 0.1)):
                for b in range(3):
                    server.metrics.on_metric(BatchMetrics(
                        job_id="lag-j", worker_id=w, batch_idx=b,
                        num_examples=8, batch_time_sec=dt))
            # healthy control tenant: must receive NO diagnosis
            led.observe_steps("ok-j", "ok-j", "w0", steps=10,
                              device_sec=1.0, examples=100,
                              input_wait_sec=0.1)
            server._history_scraper.poll_once()
            # scenario 3 — FAULT BURST on this process ("leader"): an
            # armed fault plan fires a site repeatedly + a retry loop
            # exhausts, exactly the heartbeat-adjacent burst shape
            faults.arm(faults.FaultPlan([faults.FaultRule(
                "pod.heartbeat", count=8, action="skip")]))
            for _ in range(6):
                faults.site("pod.heartbeat", pid=0)
            faults.disarm()
            with pytest.raises(RetryError):
                call_with_retry(
                    lambda: (_ for _ in ()).throw(OSError("injected")),
                    RetryPolicy(max_attempts=3, base_delay_sec=0.001,
                                max_delay_sec=0.002),
                    op="pod.report")
            # scenario 4 — SLO BREACH on stall-j (joined to its stall)
            joblog.record_event("stall-j", "slo", attainment=0.4,
                                target_sps=500.0, epoch=3)
            time.sleep(0.05)  # past the (test-sized) resolution bucket
            server._history_scraper.poll_once()
            time.sleep(0.05)
            server._history_scraper.poll_once()  # dedupe: no re-fire
            port = server.serve_tcp(0)

            assert cli_main(["obs", "doctor", "--port", str(port),
                             "--json"]) == 0
            out = json.loads(capsys.readouterr().out)
            diags = out["diagnoses"]
            by_rule = {}
            for d in diags:
                by_rule.setdefault(d["rule"], []).append(d)
            # each scenario: correct verdict, exactly once
            for rule in ("input_bound", "straggler", "infra_suspect",
                         "slo_breach"):
                assert len(by_rule.get(rule, [])) == 1, (rule, diags)
            # correct tenant/pid attribution + non-empty evidence
            assert by_rule["input_bound"][0]["job"] == "stall-j"
            assert by_rule["input_bound"][0]["evidence"]["points"]
            assert by_rule["straggler"][0]["job"] == "lag-j"
            assert (by_rule["straggler"][0]["evidence"]["slowest_worker"]
                    == "w1")
            infra = by_rule["infra_suspect"][0]
            assert infra["target"] == "leader"
            import os

            assert infra["pid"] == str(os.getpid())
            assert infra["evidence"]["events_in_window"] >= 5
            breach = by_rule["slo_breach"][0]
            assert breach["job"] == "stall-j"
            assert breach["evidence"]["cause_rule"] == "input_bound"
            # the healthy tenant got no verdict
            assert not any(d.get("job") == "ok-j" for d in diags)
            # the store header the text view renders is populated too
            assert out["history"]["series"] > 0
            # text rendering sanity (the non-json face)
            assert cli_main(["obs", "doctor", "--port", str(port)]) == 0
            text = capsys.readouterr().out
            assert "input_bound" in text and "stall-j" in text
        finally:
            faults.disarm()
            server.shutdown(timeout=60)
            joblog.clear_events()
            reset_ledger()
            faults.reset_counters()
