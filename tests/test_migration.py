"""Live migration under training — the elasticity correctness test.

The analogue of the reference's OwnershipFirstMigrationTest (jobserver/src/
test/.../integration/OwnershipFirstMigrationTest.java): run the AddVector
validator app while plans force executor add/remove + block moves
mid-training, then assert the exact expected sums — proving no push is lost
or double-applied across live re-sharding.
"""
import threading
import time

import numpy as np
import pytest

from harmony_tpu.apps.addvector import AddVectorTrainer, make_marks
from harmony_tpu.config.params import TableConfig, TrainerParams
from harmony_tpu.dolphin import TrainerContext, TrainingDataProvider, WorkerTasklet
from harmony_tpu.parallel import DevicePool
from harmony_tpu.plan import (
    AllocateOp,
    AssociateOp,
    DeallocateOp,
    ETPlan,
    MoveOp,
    PlanExecutor,
    UnassociateOp,
)
from harmony_tpu.runtime import ETMaster


def run_training_with_plans(devices, make_plan, epochs=6, nb=4, n=128):
    """Train AddVector; after each epoch fire make_plan(epoch) if not None."""
    pool = DevicePool(devices[:4])
    master = ETMaster(pool)
    exs = master.add_executors(2)
    trainer = AddVectorTrainer(num_keys=16, vector_dim=2, delta=1.0)
    handle = master.create_table(trainer.model_table_config(), [e.id for e in exs])
    params = TrainerParams(num_epochs=epochs, num_mini_batches=nb)
    ctx = TrainerContext(params=params, model_table=handle.table)
    plan_errors = []

    def on_epoch(epoch):
        plan = make_plan(master, handle, exs, epoch)
        if plan is not None:
            result = PlanExecutor(master).execute(plan)
            if not result.success:
                plan_errors.append(result.error)

    worker = WorkerTasklet(
        "mig-job",
        ctx,
        trainer,
        TrainingDataProvider(list(make_marks(n)), nb),
        handle.table.mesh,
        epoch_callback=on_epoch,
    )
    result = worker.run()
    assert not plan_errors, plan_errors
    expected = trainer.expected_value(n * epochs)
    np.testing.assert_allclose(
        np.asarray(handle.table.pull_array()), np.full((16, 2), expected)
    )
    return master, handle, result


class TestLiveMigration:
    def test_add_server_mid_training(self, devices):
        """AddOneServerOptimizer analogue: epoch 2 grows the table onto a
        fresh executor while the worker keeps training."""
        state = {}

        def make_plan(master, handle, exs, epoch):
            if epoch != 1:
                return None
            plan = ETPlan()
            alloc = plan.add_op(AllocateOp("new"))
            assoc = plan.add_op(AssociateOp(handle.table_id, "new"), depends_on=[alloc])
            plan.add_op(
                MoveOp(handle.table_id, exs[0].id, "new", 4), depends_on=[assoc]
            )
            state["grown"] = True
            return plan

        master, handle, _ = run_training_with_plans(devices, make_plan)
        assert state.get("grown")
        assert len(handle.owning_executors()) == 3

    def test_delete_server_mid_training(self, devices):
        """DeleteOneServerOptimizer analogue: epoch 3 drains an executor and
        deallocates it while the worker keeps training."""

        def make_plan(master, handle, exs, epoch):
            if epoch != 2:
                return None
            victim = exs[1].id
            n_victim = handle.block_manager.block_counts()[victim]
            plan = ETPlan()
            mv = plan.add_op(MoveOp(handle.table_id, victim, exs[0].id, n_victim))
            un = plan.add_op(UnassociateOp(handle.table_id, victim), depends_on=[mv])
            plan.add_op(DeallocateOp(victim), depends_on=[un])
            return plan

        master, handle, _ = run_training_with_plans(devices, make_plan)
        assert len(handle.owning_executors()) == 1

    def test_grow_then_shrink(self, devices):
        """Both reconfigurations in one run (epochs 1 and 3)."""
        ids = {}

        def make_plan(master, handle, exs, epoch):
            if epoch == 1:
                plan = ETPlan()
                alloc = plan.add_op(AllocateOp("v"))
                assoc = plan.add_op(AssociateOp(handle.table_id, "v"), depends_on=[alloc])
                plan.add_op(MoveOp(handle.table_id, exs[0].id, "v", 3), depends_on=[assoc])
                return plan
            if epoch == 3:
                # find the executor allocated at epoch 1 (not in exs)
                new_id = next(
                    e for e in handle.block_manager.executors
                    if e not in {x.id for x in exs}
                )
                n_new = handle.block_manager.block_counts()[new_id]
                plan = ETPlan()
                mv = plan.add_op(MoveOp(handle.table_id, new_id, exs[1].id, n_new))
                un = plan.add_op(UnassociateOp(handle.table_id, new_id), depends_on=[mv])
                plan.add_op(DeallocateOp(new_id), depends_on=[un])
                return plan
            return None

        master, handle, _ = run_training_with_plans(devices, make_plan)
        assert len(handle.owning_executors()) == 2

    def test_concurrent_migration_during_batches(self, devices):
        """Harder than the reference's epoch-boundary reconfigs: fire the
        migration from a separate thread WHILE batches are dispatching (the
        per-batch path), relying on the table lock + rebuild-on-reshard."""
        pool = DevicePool(devices[:4])
        master = ETMaster(pool)
        exs = master.add_executors(2)
        trainer = AddVectorTrainer(num_keys=16, vector_dim=2, delta=1.0)
        handle = master.create_table(trainer.model_table_config(), [e.id for e in exs])
        n, epochs, nb = 128, 8, 4
        params = TrainerParams(num_epochs=epochs, num_mini_batches=nb)
        ctx = TrainerContext(params=params, model_table=handle.table)
        # barrier forces the per-batch (non-fused) path without gating.
        worker = WorkerTasklet(
            "conc-mig",
            ctx,
            trainer,
            TrainingDataProvider(list(make_marks(n)), nb),
            handle.table.mesh,
            batch_barrier=lambda i: False,
        )
        errors = []

        def migrate():
            try:
                time.sleep(0.05)
                plan = ETPlan()
                alloc = plan.add_op(AllocateOp("m"))
                assoc = plan.add_op(
                    AssociateOp(handle.table_id, "m"), depends_on=[alloc]
                )
                plan.add_op(
                    MoveOp(handle.table_id, exs[0].id, "m", 4), depends_on=[assoc]
                )
                r = PlanExecutor(master).execute(plan)
                if not r.success:
                    errors.append(r.error)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=migrate)
        t.start()
        worker.run()
        t.join(timeout=30)
        assert not t.is_alive(), "migration thread wedged (>30s)"
        assert not errors, errors
        expected = trainer.expected_value(n * epochs)
        np.testing.assert_allclose(
            np.asarray(handle.table.pull_array()), np.full((16, 2), expected)
        )

    def test_concurrent_migration_during_epoch_window(self, devices):
        """A reshard landing MID-WINDOW: with probes off and no barrier the
        worker dispatches multi-epoch fused windows, and a concurrent
        plan-driven migration must be absorbed by the window's per-dispatch
        retry (layout race -> rebuild -> redispatch) with exact sums
        preserved."""
        pool = DevicePool(devices[:4])
        master = ETMaster(pool)
        exs = master.add_executors(2)
        trainer = AddVectorTrainer(num_keys=16, vector_dim=2, delta=1.0)
        handle = master.create_table(trainer.model_table_config(),
                                     [e.id for e in exs])
        n, epochs, nb = 128, 16, 4
        params = TrainerParams(num_epochs=epochs, num_mini_batches=nb,
                               comm_probe_period=0)  # windows active
        ctx = TrainerContext(params=params, model_table=handle.table)
        worker = WorkerTasklet(
            "win-mig", ctx, trainer,
            TrainingDataProvider(list(make_marks(n)), nb),
            handle.table.mesh,
        )
        assert worker._epoch_window_len(0, epochs) > 1
        errors = []

        def migrate():
            try:
                time.sleep(0.05)
                plan = ETPlan()
                alloc = plan.add_op(AllocateOp("wm"))
                assoc = plan.add_op(
                    AssociateOp(handle.table_id, "wm"), depends_on=[alloc]
                )
                plan.add_op(
                    MoveOp(handle.table_id, exs[0].id, "wm", 4),
                    depends_on=[assoc],
                )
                r = PlanExecutor(master).execute(plan)
                if not r.success:
                    errors.append(r.error)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=migrate)
        t.start()
        result = worker.run()
        t.join(timeout=30)
        assert not t.is_alive(), "migration thread wedged (>30s)"
        assert not errors, errors
        assert result["epochs_run"] == epochs
        expected = trainer.expected_value(n * epochs)
        np.testing.assert_allclose(
            np.asarray(handle.table.pull_array()), np.full((16, 2), expected)
        )
        assert len(handle.owning_executors()) == 3


class TestReshardPrewarm:
    def test_announce_prewarms_target_layout(self, devices):
        """The reshard announcement compiles the target layout's programs
        and pre-uploads the stacked dataset BEFORE the ownership flip
        (TableHandle._reshard_to_owners -> announce_reshard ->
        WorkerTasklet._prewarm_layout), so the post-move rebuild installs
        the pre-uploaded dataset instead of re-transferring — and exact
        sums still hold through the move."""
        from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic
        from harmony_tpu.runtime import progcache

        pool = DevicePool(devices[:2])
        master = ETMaster(pool)
        exs = master.add_executors(2)
        trainer = MLRTrainer(num_classes=8, num_features=32,
                             features_per_partition=8, step_size=0.1)
        handle = master.create_table(
            trainer.model_table_config(), [e.id for e in exs]
        )
        x, y = make_synthetic(64, num_features=32, num_classes=8)
        params = TrainerParams(num_epochs=6, num_mini_batches=4,
                               comm_probe_period=0)
        seen = {}

        def on_epoch(epoch):
            if epoch == 2:
                n = handle.block_manager.block_counts()[exs[0].id]
                before = progcache.stats()["misses"]
                handle.move_blocks(exs[0].id, exs[1].id, n)
                seen["stacked"] = worker._prewarmed_stacked
                seen["misses_during_move"] = (
                    progcache.stats()["misses"] - before
                )

        worker = WorkerTasklet(
            "prewarm-job",
            TrainerContext(params=params, model_table=handle.table),
            trainer,
            TrainingDataProvider([x, y], 4),
            handle.table.mesh,
            epoch_callback=on_epoch,
        )
        result = worker.run()
        # the move itself built the target programs (progcache misses
        # happened INSIDE move_blocks, via the announcement listener)...
        assert seen["misses_during_move"] >= 1, seen
        # ...and staged the dataset for the target layout
        assert seen["stacked"] is not None
        assert seen["stacked"][0] == handle.table.sharding
        # ...which the post-move rebuild consumed
        assert worker._prewarmed_stacked is None
        assert worker._stacked_cache is seen["stacked"][1]
        # training stayed healthy across the move
        assert result["losses"][-1] < result["losses"][0], result["losses"]
        assert len(handle.owning_executors()) == 1


class TestReshardPrewarmSparse:
    def test_hash_table_announce_prewarms(self, devices):
        """Round-3 parity: the reshard announcement pre-warms HASH-backed
        jobs too (the sparse FM/LDA shape) — the announced layout's step
        compiles via progcache before the flip, and training stays exact
        through the move."""
        from harmony_tpu.apps.widedeep import FMTrainer, make_synthetic_sparse
        from harmony_tpu.runtime import progcache

        pool = DevicePool(devices[:2])
        master = ETMaster(pool)
        exs = master.add_executors(2)
        tr = FMTrainer(vocab_size=64, num_slots=4, emb_dim=4, step_size=0.5,
                       sparse=True)
        cfg = tr.model_table_config().replace(num_blocks=16)
        handle = master.create_table(cfg, [e.id for e in exs])
        ids, y = make_synthetic_sparse(256, vocab_size=64, num_slots=4,
                                       seed=3)
        params = TrainerParams(num_epochs=6, num_mini_batches=4,
                               comm_probe_period=0)
        seen = {}

        def on_epoch(epoch):
            if epoch == 2:
                n = handle.block_manager.block_counts()[exs[0].id]
                before = progcache.stats()["misses"]
                handle.move_blocks(exs[0].id, exs[1].id, n)
                seen["misses_during_move"] = (
                    progcache.stats()["misses"] - before
                )

        worker = WorkerTasklet(
            "sp-prewarm",
            TrainerContext(params=params, model_table=handle.table),
            tr,
            TrainingDataProvider([ids, y], 4),
            handle.table.mesh,
            epoch_callback=on_epoch,
        )
        result = worker.run()
        # the announcement built the target-layout programs INSIDE the move
        assert seen["misses_during_move"] >= 1, seen
        assert result["losses"][-1] < result["losses"][0], result["losses"]
        assert len(handle.owning_executors()) == 1
        assert handle.table.overflow_count == 0


class TestSparseTableMigration:
    def test_concurrent_migration_during_sparse_training(self, devices):
        """Live plan-driven migration of a HASH-backED model table while a
        sparse FM job is mid-epoch: the table lock + commit re-homing must
        keep training correct through the ownership flip (the sparse
        analogue of test_concurrent_migration_during_batches)."""
        from harmony_tpu.apps.widedeep import FMTrainer, make_synthetic_sparse

        pool = DevicePool(devices[:4])
        master = ETMaster(pool)
        exs = master.add_executors(2)
        tr = FMTrainer(vocab_size=64, num_slots=4, emb_dim=4, step_size=2.0,
                       sparse=True)
        cfg = tr.model_table_config().replace(num_blocks=16)
        handle = master.create_table(cfg, [e.id for e in exs])
        ids, y = make_synthetic_sparse(512, vocab_size=64, num_slots=4, seed=5)
        params = TrainerParams(num_epochs=8, num_mini_batches=4)
        ctx = TrainerContext(params=params, model_table=handle.table)
        worker = WorkerTasklet(
            "sp-mig", ctx, tr,
            TrainingDataProvider([ids, y], 4),
            handle.table.mesh,
            batch_barrier=lambda i: False,  # per-batch path, no gating
        )
        errors = []

        def migrate():
            try:
                time.sleep(0.05)
                plan = ETPlan()
                alloc = plan.add_op(AllocateOp("m"))
                assoc = plan.add_op(
                    AssociateOp(handle.table_id, "m"), depends_on=[alloc]
                )
                plan.add_op(
                    MoveOp(handle.table_id, exs[0].id, "m", 4), depends_on=[assoc]
                )
                r = PlanExecutor(master).execute(plan)
                if not r.success:
                    errors.append(r.error)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=migrate)
        t.start()
        result = worker.run()
        t.join(timeout=30)
        assert not t.is_alive(), "migration thread wedged (>30s)"
        assert not errors, errors
        # training remained healthy through the migration
        assert result["losses"][-1] < result["losses"][0], result["losses"]
        assert handle.table.num_present() == len(np.unique(ids)) + tr.num_extra_rows
        assert handle.table.overflow_count == 0
        # the newly allocated executor (virtual id "m" resolved to a real
        # one by AllocateOp) really owns blocks now: three owners total
        assert len(handle.owning_executors()) == 3
