"""FIXED fixture tree: every registered instrument has its
docs/OBSERVABILITY.md metric-table row and every documented name is
registered. The metric-conventions pass must come up clean."""


def register(reg):
    reg.histogram("harmony_widget_seconds", "per-widget wall time",
                  ("job",))
