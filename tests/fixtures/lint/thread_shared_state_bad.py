"""KNOWN-BAD fixture: the `_LEG_RETRIES` bug, pre-PR-5-review shape.

A module counter mutated from pool-submitted migration legs AND reset
from the coordinating code, with no lock on either side — increments
interleave and retries vanish from the stats. The thread-shared-state
pass must flag both unguarded mutation sites. A second class-shaped
case: a worker thread and a public method both move `self._state`
without the instance lock."""
import threading
from concurrent.futures import ThreadPoolExecutor

from typing import List

_LEG_RETRIES: List[int] = [0]  # annotated, like the real blockmove.py
_RETRY_LOCK = threading.Lock()


def tcp_exchange(legs, send):
    def run_leg(leg):
        send(leg)
        _LEG_RETRIES[0] += 1  # BAD: pool thread, no _RETRY_LOCK

    with ThreadPoolExecutor(4) as pool:
        futs = [pool.submit(run_leg, leg) for leg in legs]
    return [f.result() for f in futs]


def migrate_blocks(arr, plan, send):
    _LEG_RETRIES[0] = 0  # BAD: other side of the same counter, no lock
    return tcp_exchange(plan(arr), send)


class Mover:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"

    def start(self):
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        self._state = "draining"  # BAD: worker thread, no self._lock

    def close(self):
        self._state = "closed"  # BAD: caller thread, same attribute
