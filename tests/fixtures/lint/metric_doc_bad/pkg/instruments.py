"""KNOWN-BAD fixture tree: the histogram registered below appears in
no docs/OBSERVABILITY.md metric-table row, and the doc table documents
a gauge nothing in this tree registers. The metric-conventions pass's
doc-parity directions must flag both."""


def register(reg):
    reg.histogram("harmony_widget_seconds", "per-widget wall time",
                  ("job",))  # BAD: not in the doc's metric table
