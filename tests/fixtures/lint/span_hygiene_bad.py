"""KNOWN-BAD fixture: a span opened positionally leaks on the
exception path — compute() raising skips __exit__, the span never
emits, and the trace timeline loses the failing subtree. The
span-hygiene pass must flag both opens."""
from harmony_tpu.tracing.span import trace_span


def step(compute, batch):
    cm = trace_span("dolphin.step", batch=batch)  # BAD: no `with`
    cm.__enter__()
    out = compute(batch)
    cm.__exit__(None, None, None)
    return out


def epoch(compute, batches):
    spans = [trace_span("dolphin.epoch", i=i)  # BAD: stored, never closed
             for i, _ in enumerate(batches)]
    return [compute(b) for b in batches], spans
