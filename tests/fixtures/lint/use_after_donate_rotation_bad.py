"""KNOWN-BAD fixture: ping/pong rotation leaking the dead handle.

The rotation itself is sanctioned (pure-name tuple assignment moves
handles, it never touches device memory) — but the alias now holding
the DONATED buffer is read inside the overlap window without a
rebinding fence. The use-after-donate pass must flag the read (and
only the read: the rotation lines must stay clean)."""
import jax

push_step = jax.jit(lambda ping, delta: ping + delta, donate_argnums=(0,))


def overlap_window_leak(ping, pong, deltas):
    for delta in deltas:
        pong = push_step(ping, delta)
        ping, pong = pong, ping  # rotate: dead handle now rides `pong`
        norm = pong.sum()  # BAD: reads the donated buffer, no fence
    return ping, norm
