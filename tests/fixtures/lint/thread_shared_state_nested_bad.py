"""KNOWN-BAD fixture: the `_LEG_RETRIES` bug hidden one closure deeper.

The thread entry (`_loop`) does not touch shared state itself — it
defines a nested leg function that calls `self._bump()`, and _bump_
mutates the counter. The runs-on-thread closure must follow
`self.<m>()` calls made from defs lexically nested inside thread
callables, not just from methods handed to Thread directly — exactly
the closure-heavy shape pod.py/blockmove use for per-leg work.
"""
import threading


class NestedCounter:
    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        def pump():  # nested leg function — still runs on the thread
            self._bump()
        for _ in range(3):
            pump()

    def _bump(self):
        self._n += 1  # BAD: reached from the thread via nested def, no lock

    def reset(self):
        self._n = 0  # BAD: other side of the same counter, no lock
