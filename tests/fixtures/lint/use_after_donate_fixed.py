"""FIXED fixture: the sanctioned donation shape — the call's result is
bound back onto the donated name, so every read sees the live buffer.
The use-after-donate pass must come up clean."""
import jax

train_step = jax.jit(lambda tbl, batch: tbl + batch, donate_argnums=(0,))


def run_epoch(tbl, batches):
    for batch in batches:
        tbl = train_step(tbl, batch)
    return tbl


def run_once(tbl, batch):
    tbl = train_step(tbl, batch)
    return tbl, tbl.sum()
