"""Fixture package."""
