"""KNOWN-BAD fixture tree: the tuning knob read below is documented
nowhere, and the manifest wires a ghost knob that nothing in this tree
reads (and that the docs never mention). The knob-consistency pass
must flag all three directions."""
import os


def tuning():
    return int(os.environ.get("HARMONY_SECRET_TUNING", "0"))  # undocumented


def period():
    return float(os.environ.get("HARMONY_HB_PERIOD_FIX", "2"))
