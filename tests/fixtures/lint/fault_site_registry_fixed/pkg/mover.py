"""FIXED fixture tree: every fired site has a registry row and every
row is fired. The fault-site-registry pass must come up clean."""
from harmony_tpu import faults


def send_block(block, dst):
    if faults.armed():
        faults.site("blockmove.send", block=block, dst=dst)
    return dst.push(block)


def stage_block(block, seq):
    if faults.armed():
        faults.site("blockmove.stage_write", block=block, seq=seq)
    return seq


def commit(chkp_id):
    if faults.armed():
        faults.site("chkp.commit", chkp_id=chkp_id)
    return chkp_id
