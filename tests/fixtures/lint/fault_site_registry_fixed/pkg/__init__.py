"""Fixture package."""
