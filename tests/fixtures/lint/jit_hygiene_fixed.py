"""FIXED fixture: wrappers are cached (module scope here; table._jitted
or runtime/progcache in the tree) and the step jit states its donation
intent explicitly. The jit-hygiene pass must come up clean."""
import functools

import jax


@functools.lru_cache(maxsize=None)
def _writer(spec):
    return jax.jit(spec.write_all)


def write_all(specs, values):
    for spec, value in zip(specs, values):
        _writer(spec)(value)


def train_step(tbl, batch):
    return tbl + batch


step = jax.jit(train_step, donate_argnums=(0,))
