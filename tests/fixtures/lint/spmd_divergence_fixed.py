"""FIXED fixture: the PR 5 shape as shipped — the env-derived pipeline
decision is fenced to single-process meshes by a topology guard in the
same condition chain, so spanning meshes keep the one collective
import. The spmd-divergence pass must come up clean."""
import os


def _chkp_io_threads():
    return max(1, int(os.environ.get("HARMONY_CHKP_IO_THREADS", "4")))


def mesh_spans_processes(mesh):
    return len({d.process_index for d in mesh.devices.flat}) > 1


def restore_inner(handle, info, read_block, mesh):
    threads = min(_chkp_io_threads(), max(1, len(info.block_ids)))
    pipelined = (threads > 1 and not info.sparse
                 and not mesh_spans_processes(mesh))
    blocks = {}
    for bid in info.block_ids:
        blocks[bid] = read_block(bid)
        if pipelined and len(blocks) >= 16:
            handle.table.import_blocks(blocks)  # single-process only
            blocks = {}
    handle.table.import_blocks(blocks)
