"""KNOWN-BAD fixture: donated buffer touched after the step.

Both shapes the pass pins: a read of the donated name after the call
without rebinding, and a loop that re-donates the same dead handle
every iteration. The use-after-donate pass must flag both."""
import jax

train_step = jax.jit(lambda tbl, batch: tbl + batch, donate_argnums=(0,))


def run_epoch(tbl, batches):
    for batch in batches:
        out = train_step(tbl, batch)  # BAD: tbl never rebound in the loop
    return out


def run_once(tbl, batch):
    out = train_step(tbl, batch)
    return out, tbl.sum()  # BAD: tbl was donated on the line above
