"""KNOWN-BAD fixture: the PR 6 retrace-every-call bug — a fresh jit
wrapper built and invoked in one expression inside a per-call lambda
(apps/nmf.py's old shape), plus a step-shaped jit silent about
donation. The jit-hygiene pass must flag both."""
import jax


def write_all(specs, values):
    for spec, value in zip(specs, values):
        jax.jit(spec.write_all)(value)  # BAD: construct-and-call


def train_step(tbl, batch):
    return tbl + batch


step = jax.jit(train_step)  # BAD: step-shaped, donation intent unstated
