"""KNOWN-BAD fixture tree: the rule declared below appears in no
docs/OBSERVABILITY.md rule-catalog row, and the catalog documents a
rule nothing in this tree declares. The metric-conventions pass's
doctor-rule parity directions must flag both."""


def doctor_rule(name, description):
    def deco(fn):
        return fn

    return deco


@doctor_rule("phantom_stall", "fires when nothing documents it")
def _phantom_stall(ctx):  # BAD: not in the doc's rule catalog
    return []
