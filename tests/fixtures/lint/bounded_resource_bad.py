"""Fixture: the unbounded server shapes bounded-resource must catch.

The seeded regression is the pre-PR-17 ``serve_tcp``: a thread per
accepted connection, plus the uncapped feed queue and the hand-rolled
connection list.
"""
import queue
import socket
import threading

_BACKLOG = queue.Queue()  # line 11: uncapped ingest queue


def _handle(conn):
    with conn:
        conn.recv(65536)


def serve(port: int) -> None:
    sock = socket.socket()
    sock.bind(("127.0.0.1", port))
    sock.listen(64)
    pending = []
    while True:
        conn, _ = sock.accept()
        # one thread per connection — unbounded under a storm
        threading.Thread(target=_handle, args=(conn,),  # line 27
                         daemon=True).start()
        pending.append(conn)  # line 29: hand-rolled unbounded queue
