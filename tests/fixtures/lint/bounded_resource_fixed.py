"""Fixture: the bounded twin — fixed worker pool over a capped queue,
shed (close) on Full. bounded-resource must come up clean here."""
import queue
import socket
import threading

_BACKLOG: "queue.Queue" = queue.Queue(maxsize=64)


def _worker() -> None:
    while True:
        conn = _BACKLOG.get()
        if conn is None:
            return
        with conn:
            conn.recv(65536)


def serve(port: int) -> None:
    sock = socket.socket()
    sock.bind(("127.0.0.1", port))
    sock.listen(64)
    # fixed pool, spawned ONCE before the accept loop
    for _ in range(4):
        threading.Thread(target=_worker, daemon=True).start()
    while True:
        conn, _ = sock.accept()
        try:
            _BACKLOG.put_nowait(conn)
        except queue.Full:
            conn.close()  # shed at admission, never accept-then-wedge
