"""KNOWN-BAD fixture: the PR 5 chunk-count bug, pre-fix shape.

The restore chunk count derives from this process's
HARMONY_CHKP_IO_THREADS; `import_blocks` on a spanning mesh is an
SPMD-collective dispatch, so env skew across the pod diverges the
collective sequence and wedges the restore. The spmd-divergence pass
must flag the gated `import_blocks` call."""
import os


def _chkp_io_threads():
    return max(1, int(os.environ.get("HARMONY_CHKP_IO_THREADS", "4")))


def restore_inner(handle, info, read_block):
    threads = min(_chkp_io_threads(), max(1, len(info.block_ids)))
    pipelined = threads > 1 and not info.sparse
    blocks = {}
    for bid in info.block_ids:
        blocks[bid] = read_block(bid)
        if pipelined and len(blocks) >= 16:
            handle.table.import_blocks(blocks)  # BAD: env-steered dispatch
            blocks = {}
    handle.table.import_blocks(blocks)
