"""FIXED twin of doctor_rules_bad: every declared rule has a catalog
row and every catalog row names a declared rule."""


def doctor_rule(name, description):
    def deco(fn):
        return fn

    return deco


@doctor_rule("phantom_stall", "documented in the catalog below")
def _phantom_stall(ctx):
    return []
