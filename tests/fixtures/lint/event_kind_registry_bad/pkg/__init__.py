"""Fixture package."""
