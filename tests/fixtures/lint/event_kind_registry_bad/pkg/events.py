"""KNOWN-BAD fixture tree for the event-kind-registry pass, all three
directions red at once:

* ``mystery_kind`` is emitted but never declared in ``EVENT_KINDS`` —
  the typo'd-kind failure: it records fine and correlates as nothing;
* the catalog declares ``ghost_kind`` but the doc table has no row —
  operators grepping the docs never learn it exists;
* the doc table has a ``phantom_kind`` row the catalog never declares —
  a dead row documenting events that can never appear.
"""

EVENT_KINDS = {
    "recovery": "pkg/events.py: attempt recovered",
    "ghost_kind": "pkg/events.py: declared but never tabled",
}


def record_event(job_id, kind, **fields):
    return {"kind": kind, **fields}


def on_recover(job_id):
    record_event(job_id, "recovery", outcome="ok")
    record_event(job_id, "mystery_kind", oops=True)  # undeclared
