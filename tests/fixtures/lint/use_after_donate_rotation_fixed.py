"""FIXED fixture: ping/pong double-buffer rotation, fenced.

The alternating donated names rebind on the NEXT iteration via the
rotation (`ping, pong = pong, ping` after `pong = push_step(ping, d)`),
which the use-after-donate pass must NOT false-positive on — the tuple
assignment moves handles, it reads nothing from the device. Where the
dead alias IS needed inside the overlap window, a staleness fence
republishes it first (`pong = fence(pong)` rebinds before the read).
The pass must come up clean on both shapes."""
import jax

push_step = jax.jit(lambda ping, delta: ping + delta, donate_argnums=(0,))
fence = jax.jit(lambda view: view * 1.0)


def rotate_only(ping, pong, deltas):
    for delta in deltas:
        pong = push_step(ping, delta)
        ping, pong = pong, ping  # dead handle parks on `pong`, unread
    return ping


def rotate_with_fence(ping, pong, deltas):
    norm = None
    for delta in deltas:
        pong = push_step(ping, delta)
        ping, pong = pong, ping
        pong = fence(ping)  # staleness fence: rebind before the read
        norm = pong.sum()
    return ping, norm
