"""FIXED fixture: spans opened via `with` (or owned by an ExitStack)
close — and emit — on every path. The span-hygiene pass must come up
clean."""
import contextlib

from harmony_tpu.tracing.span import trace_span


def step(compute, batch):
    with trace_span("dolphin.step", batch=batch):
        return compute(batch)


def epoch(compute, batches):
    with contextlib.ExitStack() as stack:
        stack.enter_context(trace_span("dolphin.epoch"))
        return [compute(b) for b in batches]
