"""FIXED fixture tree: every knob read is documented and every
manifest-wired knob is read and documented. The knob-consistency pass
must come up clean."""
import os


def tuning():
    return int(os.environ.get("HARMONY_SECRET_TUNING", "0"))


def period():
    return float(os.environ.get("HARMONY_HB_PERIOD_FIX", "2"))
