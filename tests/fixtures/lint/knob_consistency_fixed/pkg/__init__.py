"""Fixture package."""
