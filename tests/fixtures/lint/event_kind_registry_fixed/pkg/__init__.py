"""Fixture package."""
