"""FIXED twin of event_kind_registry_bad: every emitted kind is
declared, every declared kind is tabled, every row is declared."""

EVENT_KINDS = {
    "recovery": "pkg/events.py: attempt recovered",
    "mystery_kind": "pkg/events.py: now declared (and tabled)",
}


def record_event(job_id, kind, **fields):
    return {"kind": kind, **fields}


def on_recover(job_id):
    record_event(job_id, "recovery", outcome="ok")
    record_event(job_id, "mystery_kind", oops=False)
