"""KNOWN-BAD fixture tree: a typo'd site (``blockmove.sendd``) the
registry never heard of — a plan armed at ``blockmove.send`` silently
injects nothing — and the registry's ``chkp.commit`` row has no code
site left. The fault-site-registry pass must flag both directions."""
from harmony_tpu import faults


def send_block(block, dst):
    if faults.armed():
        faults.site("blockmove.sendd", block=block, dst=dst)  # typo'd
    return dst.push(block)


def stage_block(block, seq):
    if faults.armed():
        faults.site("blockmove.stage_write", block=block, seq=seq)
    return seq
