"""Fixture package."""
