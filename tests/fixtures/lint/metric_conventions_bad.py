"""KNOWN-BAD fixture: instrument declarations that would fail the
scrape-time exposition lint — a harmony_* counter without _total, a
histogram without a base-unit suffix, and an empty HELP string. The
metric-conventions pass must flag all three."""


def register(reg):
    reg.counter("harmony_progcache_events", "hits and misses",
                ("result",))  # BAD: counter must end _total
    reg.histogram("harmony_step_latency", "per-step wall time",
                  ("job",))  # BAD: no _seconds/_bytes unit suffix
    reg.gauge("harmony_inflight_bytes", "")  # BAD: empty HELP
