"""FIXED fixture: every mutation site of the shared counter/attribute
holds the common lock (the shape blockmove.py ships since PR 5). The
thread-shared-state pass must come up clean."""
import threading
from concurrent.futures import ThreadPoolExecutor

from typing import List

_LEG_RETRIES: List[int] = [0]
_RETRY_LOCK = threading.Lock()


def tcp_exchange(legs, send):
    def run_leg(leg):
        send(leg)
        with _RETRY_LOCK:
            _LEG_RETRIES[0] += 1

    with ThreadPoolExecutor(4) as pool:
        futs = [pool.submit(run_leg, leg) for leg in legs]
    return [f.result() for f in futs]


def migrate_blocks(arr, plan, send):
    with _RETRY_LOCK:
        _LEG_RETRIES[0] = 0
    return tcp_exchange(plan(arr), send)


class Mover:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"

    def start(self):
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        with self._lock:
            self._state = "draining"

    def close(self):
        with self._lock:
            self._state = "closed"
