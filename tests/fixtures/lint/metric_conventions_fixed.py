"""FIXED fixture: the same instruments named per the exposition
contract (docs/OBSERVABILITY.md). The metric-conventions pass must
come up clean."""


def register(reg):
    reg.counter("harmony_progcache_events_total", "hits and misses",
                ("result",))
    reg.histogram("harmony_step_latency_seconds", "per-step wall time",
                  ("job",))
    reg.gauge("harmony_inflight_bytes", "bytes currently in flight")
