"""Transformer LM: forward correctness, SP step vs single-device math,
and end-to-end training through the framework's worker loop."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harmony_tpu.models import (
    TransformerConfig,
    TransformerLM,
    TransformerTrainer,
    make_lm_data,
)
from harmony_tpu.models.transformer import make_sp_train_step
from harmony_tpu.parallel import build_mesh

CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_seq=64, attn="blockwise")


def test_forward_shapes_and_finite():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(make_lm_data(4, 32, CFG.vocab_size))
    logits = model.apply(params, tokens)
    assert logits.shape == (4, 32, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_loss_decreases_plain_sgd():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(make_lm_data(16, 33, CFG.vocab_size))

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(model.loss)(p, tokens)
        return jax.tree.map(lambda a, b: a - 0.5 * b, p, g), loss

    losses = []
    for _ in range(20):
        params, loss = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.3, losses


def test_sp_step_matches_single_device(devices):
    """The sharded (data=2, seq=4) step computes the same loss and the same
    updated params as unsharded full-batch math."""
    mesh = build_mesh(devices, data=2, seq=4, model=1)
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(1))
    tokens = jnp.asarray(make_lm_data(4, 32, CFG.vocab_size, seed=2))

    # donate=False: this parity test reuses the pre-step params below
    sp_step = make_sp_train_step(model, mesh, learning_rate=0.1, donate=False)
    new_sp, loss_sp = sp_step(params, tokens)

    def ref_loss(p):
        logits = model.apply(p, tokens)
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1)
        mask = jnp.concatenate(
            [jnp.ones_like(tokens[:, 1:], jnp.float32),
             jnp.zeros_like(tokens[:, :1], jnp.float32)], axis=1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return (-ll * mask).sum() / mask.sum()

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    new_ref = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads_ref)

    np.testing.assert_allclose(float(loss_sp), float(loss_ref), atol=1e-5)
    for a, b in zip(jax.tree.leaves(new_sp), jax.tree.leaves(new_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_sp_training_loop_learns(devices):
    mesh = build_mesh(devices, data=1, seq=8, model=1)
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(3))
    tokens = jnp.asarray(make_lm_data(8, 64, CFG.vocab_size, seed=4))
    step = make_sp_train_step(model, mesh, learning_rate=0.5)
    first = last = None
    for i in range(15):
        params, loss = step(params, tokens)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first - 0.3, (first, last)


def test_trainer_spi_through_worker_loop(mesh8):
    """The LM trains through WorkerTasklet + DenseTable like any app."""
    from harmony_tpu.config.params import TrainerParams
    from harmony_tpu.dolphin import TrainerContext, TrainingDataProvider, WorkerTasklet
    from harmony_tpu.table import DenseTable, TableSpec

    trainer = TransformerTrainer(CFG, row_width=256, step_size=0.5)
    table = DenseTable(TableSpec(trainer.model_table_config()), mesh8)
    tokens = make_lm_data(16, 33, CFG.vocab_size, seed=5)
    params = TrainerParams(num_epochs=4, num_mini_batches=2)
    ctx = TrainerContext(params=params, model_table=table)
    worker = WorkerTasklet(
        "lm", ctx, trainer, TrainingDataProvider([tokens], 2), mesh8
    )
    result = worker.run()
    losses = result["losses"]
    assert losses[-1] < losses[0], losses
    ev = worker.evaluate((tokens,))
    assert np.isfinite(float(ev["loss"]))


class TestStatefulOptimizers:
    def _train(self, optimizer, mesh, lr, epochs=5):
        from harmony_tpu.config.params import TrainerParams
        from harmony_tpu.dolphin import TrainerContext, TrainingDataProvider, WorkerTasklet
        from harmony_tpu.table import DenseTable, TableSpec

        trainer = TransformerTrainer(CFG, row_width=256, step_size=lr,
                                     optimizer=optimizer)
        table = DenseTable(TableSpec(trainer.model_table_config()), mesh)
        tokens = make_lm_data(16, 33, CFG.vocab_size, seed=7)
        params = TrainerParams(num_epochs=epochs, num_mini_batches=2)
        worker = WorkerTasklet(
            f"lm-{optimizer}", TrainerContext(params=params, model_table=table),
            trainer, TrainingDataProvider([tokens], 2), mesh,
        )
        return trainer, table, worker.run()

    def test_adam_learns_and_tracks_steps(self, mesh8):
        trainer, table, result = self._train("adam", mesh8, lr=3e-3)
        assert result["losses"][-1] < result["losses"][0], result["losses"]
        rows = np.asarray(table.pull_array())
        # counter row tallies exactly epochs x batches pushes
        assert rows[-1, 0] == 5 * 2
        # second-moment section is strictly non-negative and non-trivial
        v = rows[2 * trainer.num_rows:3 * trainer.num_rows].reshape(-1)
        assert (v >= -1e-12).all() and float(np.abs(v).sum()) > 0

    def test_momentum_learns(self, mesh8):
        _, _, result = self._train("momentum", mesh8, lr=0.05)
        assert result["losses"][-1] < result["losses"][0], result["losses"]

    def test_unknown_optimizer_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="unknown optimizer"):
            TransformerTrainer(CFG, optimizer="lion")

    def test_optimizer_state_survives_checkpoint_restore(self, mesh8, tmp_path, devices):
        """Adam state rides the table: checkpoint -> restore -> keep
        training, counter and moments intact."""
        from harmony_tpu.checkpoint.manager import CheckpointManager
        from harmony_tpu.parallel import DevicePool
        from harmony_tpu.runtime.master import ETMaster

        trainer, table, _ = self._train("adam", mesh8, lr=3e-3, epochs=2)
        master = ETMaster(DevicePool(devices))
        execs = [e.id for e in master.add_executors(4)]
        handle = master.create_table(
            trainer.model_table_config(table_id="lm-chk"), execs)
        handle.table.commit(table.array)  # hand the trained state over
        mgr = CheckpointManager(str(tmp_path / "t"), str(tmp_path / "c"))
        cid = mgr.checkpoint(handle, commit=True)
        restored = mgr.restore(master, cid, execs[:2], table_id="lm-chk-2")
        rows = np.asarray(restored.table.pull_array())
        assert rows[-1, 0] == 2 * 2  # step counter survived the round trip


def test_parallel_step_matches_single_device(devices):
    """The full 3-axis step (data=2, seq=2, model=2: ring attention + Megatron
    column/row TP) computes the same loss and updated params as unsharded
    full-batch math — including replicated-leaf grads, which must be psum'd
    over the model axis through the forward psums."""
    from harmony_tpu.models.transformer import (
        make_parallel_train_step,
        to_tp_params,
    )

    mesh = build_mesh(devices, data=2, seq=2, model=2)
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(3))
    tokens = jnp.asarray(make_lm_data(4, 32, CFG.vocab_size, seed=4))

    # donate=False: this parity test reuses the pre-step params below
    step, shard_params = make_parallel_train_step(model, mesh, learning_rate=0.1,
                                                  donate=False)
    tp_params = shard_params(params)
    new_tp, loss_tp = step(tp_params, tokens)

    def ref_loss(p):
        logits = model.apply(p, tokens)
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1)
        mask = jnp.concatenate(
            [jnp.ones_like(tokens[:, 1:], jnp.float32),
             jnp.zeros_like(tokens[:, :1], jnp.float32)], axis=1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return (-ll * mask).sum() / mask.sum()

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    new_ref = to_tp_params(
        jax.tree.map(lambda p, g: p - 0.1 * g, params, grads_ref)
    )

    np.testing.assert_allclose(float(loss_tp), float(loss_ref), atol=1e-5)
    flat_tp = jax.tree_util.tree_flatten_with_path(new_tp)[0]
    flat_ref = dict(jax.tree_util.tree_flatten_with_path(new_ref)[0])
    for path, a in flat_tp:
        b = flat_ref[path]
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4,
            err_msg=jax.tree_util.keystr(path),
        )


def test_parallel_step_rejects_bad_tp(devices):
    from harmony_tpu.models.transformer import make_parallel_train_step

    mesh = build_mesh(devices, data=1, seq=1, model=8)
    model = TransformerLM(CFG)  # n_heads=2 < tp=8
    with pytest.raises(ValueError):
        make_parallel_train_step(model, mesh)


def test_sp_step_a2a_matches_ring(devices):
    """The a2a sequence-parallel tier trains identically to ring (both are
    exact attention; same grads to f32 tolerance)."""
    import dataclasses

    mesh = build_mesh(devices, data=4, seq=2, model=1)
    tokens = jnp.asarray(make_lm_data(4, 32, CFG.vocab_size, seed=6))
    results = {}
    for impl in ("ring", "a2a"):
        cfg = dataclasses.replace(CFG, sp_attn=impl)
        model = TransformerLM(cfg)
        params = model.init(jax.random.PRNGKey(7))
        step = make_sp_train_step(model, mesh, learning_rate=0.1)
        new_p, loss = step(params, tokens)
        results[impl] = (new_p, float(loss))
    np.testing.assert_allclose(results["ring"][1], results["a2a"][1], atol=1e-5)
    for a, b in zip(jax.tree.leaves(results["ring"][0]),
                    jax.tree.leaves(results["a2a"][0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_parallel_step_a2a_tier(devices):
    """sp_attn='a2a' is honored by the 3-axis step (heads-per-TP-shard must
    divide the seq axis) and trains to the same result as ring."""
    import dataclasses

    from harmony_tpu.models.transformer import make_parallel_train_step

    cfg4 = dataclasses.replace(CFG, n_heads=4, sp_attn="a2a")
    mesh = build_mesh(devices, data=2, seq=2, model=2)
    tokens = jnp.asarray(make_lm_data(4, 32, CFG.vocab_size, seed=8))
    outs = {}
    for impl in ("ring", "a2a"):
        cfg = dataclasses.replace(cfg4, sp_attn=impl)
        model = TransformerLM(cfg)
        params = model.init(jax.random.PRNGKey(9))
        step, shard = make_parallel_train_step(model, mesh, learning_rate=0.1)
        new_p, loss = step(shard(params), tokens)
        outs[impl] = float(loss)
    np.testing.assert_allclose(outs["ring"], outs["a2a"], atol=1e-5)
    # indivisible: 2 heads / tp=2 -> 1 head per shard, seq axis 2
    bad = dataclasses.replace(CFG, sp_attn="a2a")
    with pytest.raises(ValueError, match="divisible"):
        make_parallel_train_step(TransformerLM(bad), mesh)


def test_config_rejects_unknown_sp_attn():
    import dataclasses

    with pytest.raises(ValueError, match="sp_attn"):
        dataclasses.replace(CFG, sp_attn="alltoall")


def test_remat_same_loss_and_grads():
    """remat=True changes memory scheduling, not math: identical loss and
    gradients to the plain forward."""
    import dataclasses

    model = TransformerLM(CFG)
    model_r = TransformerLM(dataclasses.replace(CFG, remat=True))
    params = model.init(jax.random.PRNGKey(11))
    tokens = jnp.asarray(make_lm_data(4, 32, CFG.vocab_size, seed=12))
    l0, g0 = jax.value_and_grad(model.loss)(params, tokens)
    l1, g1 = jax.value_and_grad(model_r.loss)(params, tokens)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_load_text_tokens_and_trains(tmp_path):
    """Real-file LM data: byte-level tokenization feeds the same training
    path as synthetic data, end to end through a jobserver job."""
    import jax

    from harmony_tpu.config.params import JobConfig, TrainerParams
    from harmony_tpu.jobserver import JobServer
    from harmony_tpu.models.transformer import load_text_tokens
    from harmony_tpu.parallel import DevicePool

    p = tmp_path / "corpus.txt"
    p.write_text("the quick brown fox jumps over the lazy dog. " * 200)
    toks = load_text_tokens(str(p), seq_len=33)
    assert toks.dtype == np.int32 and toks.shape[1] == 33
    assert toks.min() >= 0 and toks.max() < 256

    with pytest.raises(ValueError, match="windows"):
        load_text_tokens(str(p), seq_len=33, num_seqs=10**6)

    server = JobServer(1, device_pool=DevicePool(jax.devices()[:1]))
    server.start()
    cfg = JobConfig(
        job_id="lm-file", app_type="dolphin",
        trainer="harmony_tpu.models.transformer:TransformerTrainer",
        params=TrainerParams(
            num_epochs=4, num_mini_batches=2,
            app_params={"vocab_size": 256, "d_model": 32, "n_heads": 2,
                        "n_layers": 1, "d_ff": 64, "max_seq": 32,
                        "step_size": 0.3},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.models.transformer:load_text_tokens",
              "data_args": {"path": str(p), "seq_len": 33, "num_seqs": 64}},
    )
    result = server.submit(cfg).result(timeout=300)
    server.shutdown(timeout=60)
    losses = result["workers"]["lm-file/w0"]["losses"]
    assert losses[-1] < losses[0], losses  # real text is learnable


def test_init_numpy_matches_init_layout():
    """init_numpy (no jax ops; used by the graft entry point) must mirror
    init's tree structure, shapes and dtypes exactly — for dense AND MoE
    configs."""
    import jax

    from harmony_tpu.models import TransformerConfig, TransformerLM

    for kw in ({}, {"moe_experts": 2, "moe_every": 2}):
        cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                n_layers=2, d_ff=32, max_seq=16, **kw)
        model = TransformerLM(cfg)
        a = model.init(jax.random.PRNGKey(0))
        b = model.init_numpy()
        assert (jax.tree_util.tree_structure(a)
                == jax.tree_util.tree_structure(b))
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert la.shape == lb.shape and la.dtype == lb.dtype
