"""Control-plane HA: durable replicated job log, leader lease, warm
standby takeover, client failover (harmony_tpu/jobserver/halog.py +
lease.py + ha.py).

Fast tier. The real-process chaos acceptance (leader KILLED mid-epoch
under a deterministic plan, loss parity through client failover) lives
in tests/test_ha_pod.py (slow tier); this file pins the mechanisms:
CRC-framed append/replay, torn-tail truncation, fenced epochs (log,
replay, pod follower), standby replication catch-up, lease election,
an in-process takeover that re-arms an in-flight submission, the
NOT_LEADER redirect, the joblog LRU eviction regression, and the
leader_flap doctor rule.
"""
import json
import os
import socket
import threading
import time

import pytest

from harmony_tpu.config.params import JobConfig, TrainerParams
from harmony_tpu.jobserver import joblog
from harmony_tpu.jobserver.halog import (
    DurableJobLog,
    LogReceiver,
    LogReplicator,
    ReplayState,
    StaleEpochError,
    scan_records,
)
from harmony_tpu.jobserver.lease import LeaseManager


def _wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


# -- durable log ------------------------------------------------------------


class TestDurableLog:
    def test_append_replay_roundtrip(self, tmp_path):
        path = str(tmp_path / "job.walog")
        log = DurableJobLog(path)
        e1 = log.append("submission", job_id="j1", config={"a": 1})
        e2 = log.append("dispatch", job_id="j1", executors=["e0", "e1"])
        e3 = log.append("job_done", job_id="j1", ok=True)
        log.close()
        reopened = DurableJobLog(path)
        assert reopened.torn_recovered == 0
        entries = reopened.entries()
        assert [e["seq"] for e in entries] == [1, 2, 3]
        assert entries[0]["config"] == {"a": 1}
        assert entries[1]["executors"] == ["e0", "e1"]
        assert entries[2]["ok"] is True
        assert [e["kind"] for e in entries] == [
            e1["kind"], e2["kind"], e3["kind"]]
        # the continuation keeps seq monotonic past the recovered tail
        e4 = reopened.append("submission", job_id="j2", config={})
        assert e4["seq"] == 4
        reopened.close()

    def test_torn_tail_truncated_on_open(self, tmp_path):
        path = str(tmp_path / "job.walog")
        log = DurableJobLog(path)
        log.append("submission", job_id="j1", config={})
        log.append("dispatch", job_id="j1")
        log.close()
        good_size = os.path.getsize(path)
        # a crash mid-append: half a header + garbage
        with open(path, "ab") as f:
            f.write(b"\x40\x00\x00\x00\xde\xad\xbe\xefgarbage")
        entries, good, torn = scan_records(path)
        assert len(entries) == 2 and good == good_size and torn > 0
        reopened = DurableJobLog(path)  # recovery truncates the tail
        assert reopened.torn_recovered > 0
        assert os.path.getsize(path) == good_size
        # and the log is APPENDABLE again, replaying cleanly
        reopened.append("job_done", job_id="j1", ok=False, error="x")
        reopened.close()
        entries, _good, torn = scan_records(path)
        assert torn == 0
        assert [e["kind"] for e in entries] == [
            "submission", "dispatch", "job_done"]
        assert [e["seq"] for e in entries] == [1, 2, 3]

    def test_fenced_epoch_rejects_deposed_writer(self, tmp_path):
        log = DurableJobLog(str(tmp_path / "job.walog"))
        log.append("submission", job_id="j1", epoch=1, config={})
        log.set_epoch(3)  # a successor took over at epoch 3
        with pytest.raises(StaleEpochError):
            log.append("dispatch", job_id="j1", epoch=2)
        with pytest.raises(StaleEpochError):
            log.set_epoch(2)
        log.append("dispatch", job_id="j1", epoch=3)  # the successor's ok
        log.close()

    def test_replay_fences_stale_epoch_entries(self):
        # entries as a deposed leader's late write would leave them:
        # epoch regresses mid-stream — replay must reject, not apply
        entries = [
            {"seq": 1, "epoch": 1, "kind": "submission", "job": "a",
             "config": {"job_id": "a"}},
            {"seq": 2, "epoch": 2, "kind": "leader_takeover", "job": None},
            {"seq": 3, "epoch": 1, "kind": "job_done", "job": "a",
             "ok": True},  # stale: epoch 1 after epoch 2
            {"seq": 4, "epoch": 2, "kind": "submission", "job": "b",
             "config": {"job_id": "b"}},
        ]
        st = ReplayState.from_entries(entries)
        assert st.rejected_stale == 1
        # the stale job_done was NOT applied: "a" is still in flight
        assert sorted(st.in_flight()) == ["a", "b"]
        assert st.max_epoch == 2
        assert len(st.takeovers) == 1

    def test_replay_state_lifecycle(self, tmp_path):
        log = DurableJobLog(str(tmp_path / "job.walog"))
        log.append("submission", job_id="a", config={"job_id": "a"})
        log.append("dispatch", job_id="a", attempt=0)
        log.append("chkp_chain", job_id="a", chkp_id="a:model-3-x")
        log.append("elastic_shrink", job_id="a", attempt=2)
        log.append("submission", job_id="b", config={"job_id": "b"})
        log.append("job_done", job_id="b", ok=True)
        st = ReplayState.from_entries(log.entries())
        assert st.in_flight() == ["a"]
        assert st.chains["a"] == "a:model-3-x"
        assert st.attempts["a"] == 2
        assert "b" in st.done
        log.close()


# -- replication ------------------------------------------------------------


class TestReplication:
    def test_standby_catch_up_after_gap(self, tmp_path):
        leader = DurableJobLog(str(tmp_path / "leader.walog"))
        standby = DurableJobLog(str(tmp_path / "standby.walog"))
        # entries BEFORE the receiver exists: the catch-up prefix
        for i in range(3):
            leader.append("submission", job_id=f"j{i}", config={})
        recv = LogReceiver(standby, port=0)
        port = recv.start()
        repl = LogReplicator(leader, [f"127.0.0.1:{port}"])
        repl.start()
        _wait_for(lambda: standby.last_seq == 3, msg="catch-up")
        # live streaming
        leader.append("dispatch", job_id="j0")
        _wait_for(lambda: standby.last_seq == 4, msg="live entry")
        # a GAP: the standby goes away, the leader keeps appending
        repl.stop()
        recv.stop()
        for i in range(4):
            leader.append("job_done", job_id=f"j{i}", ok=True)
        assert standby.last_seq == 4
        # reconnect: the handshake's last_seq drives gap repair
        recv2 = LogReceiver(standby, port=0)
        port2 = recv2.start()
        repl2 = LogReplicator(leader, [f"127.0.0.1:{port2}"])
        repl2.start()
        _wait_for(lambda: standby.last_seq == leader.last_seq,
                  msg="gap repair")
        ours = [(e["seq"], e["kind"], e["job"]) for e in standby.entries()]
        theirs = [(e["seq"], e["kind"], e["job"]) for e in leader.entries()]
        assert ours == theirs
        repl2.stop()
        recv2.stop()
        leader.close()
        standby.close()


# -- lease election ---------------------------------------------------------


class TestLease:
    def test_election_renewal_and_deposition(self, tmp_path):
        lost = []
        a = LeaseManager(str(tmp_path), "replica-a", lease_s=0.5,
                         on_lost=lambda: lost.append("a"),
                         addr="127.0.0.1:1001")
        b = LeaseManager(str(tmp_path), "replica-b", lease_s=0.5,
                         addr="127.0.0.1:1002")
        assert a.try_acquire()
        assert a.epoch == 1 and a.is_valid()
        assert not b.try_acquire()  # a live peer holds it
        assert a.renew()
        # the holder dies (stops renewing): the lease runs out and the
        # standby wins with a BUMPED epoch
        time.sleep(0.6)
        assert not a.is_valid()  # local half: self-deposed, no clock trust
        assert b.try_acquire()
        assert b.epoch == 2
        assert b.previous and b.previous["holder"] == "replica-a"
        # the old holder's next renewal observes the successor
        assert not a.renew()
        assert lost == ["a"]
        b.release()

    def test_release_hands_off_immediately(self, tmp_path):
        a = LeaseManager(str(tmp_path), "a", lease_s=30.0)
        b = LeaseManager(str(tmp_path), "b", lease_s=30.0)
        assert a.try_acquire()
        assert not b.try_acquire()
        a.release()
        assert b.try_acquire()  # no 30s wait
        assert b.epoch == 2


# -- joblog eviction regression ---------------------------------------------


class TestJoblogEviction:
    def test_busy_job_survives_eviction(self):
        """Regression (PR 14): the _EVENTS_MAX_JOBS loop used to pop in
        dict-insertion order regardless of activity, so a long-lived
        BUSY job inserted first was evicted while dead jobs lingered.
        Eviction is now least-recently-appended."""
        joblog.clear_events()
        try:
            cap = joblog._EVENTS_MAX_JOBS
            joblog.record_event("busy", "epoch", i=-1)  # inserted FIRST
            for i in range(cap + 16):
                joblog.record_event(f"dead-{i}", "done", i=i)
                # the busy job keeps appending throughout
                joblog.record_event("busy", "epoch", i=i)
            events = joblog.job_events()
            assert "busy" in events, "active job evicted by idle ones"
            # the oldest IDLE jobs are the ones that went
            assert "dead-0" not in events
            assert len(events) <= cap
        finally:
            joblog.clear_events()


# -- leader_flap doctor rule -------------------------------------------------


class TestLeaderFlap:
    def test_two_takeovers_in_window_diagnose_flap(self):
        from harmony_tpu.metrics.doctor import Doctor
        from harmony_tpu.metrics.history import HistoryStore

        joblog.clear_events()
        try:
            joblog.record_event("__ha__", "leader_takeover",
                                old_leader="a", new_leader="b", epoch=2)
            joblog.record_event("__ha__", "leader_takeover",
                                old_leader="b", new_leader="a", epoch=3)
            doc = Doctor(HistoryStore(), window=900.0)
            fresh = doc.diagnose()
            flaps = [d for d in fresh if d.rule == "leader_flap"]
            assert len(flaps) == 1
            assert flaps[0].target == "control-plane"
            assert flaps[0].evidence["count"] == 2
            # one takeover is recovery, not churn: below the threshold
            joblog.clear_events()
            joblog.record_event("__ha__", "leader_takeover",
                                old_leader="a", new_leader="b", epoch=4)
            doc2 = Doctor(HistoryStore(), window=900.0)
            assert not [d for d in doc2.diagnose()
                        if d.rule == "leader_flap"]
        finally:
            joblog.clear_events()


# -- pod follower fencing ----------------------------------------------------


class TestFollowerFencing:
    def test_follower_rejects_stale_epoch_run_job(self):
        """A deposed leader's late RUN_JOB (lower leader_epoch than the
        follower has seen) is fenced: rejected with an explicit
        JOB_DONE so the stale leader's wait fails fast."""
        from harmony_tpu.jobserver.pod import PodFollower

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        box = {}

        def leader_side():
            conn, _ = srv.accept()
            f = conn.makefile("r")
            assert json.loads(f.readline())["cmd"] == "JOIN"
            # the CURRENT leader's epoch reaches the follower first
            conn.sendall((json.dumps(
                {"cmd": "PLAN", "job_id": "zz", "plan": {"epoch": 99},
                 "leader_epoch": 5}) + "\n").encode())
            # ...then a DEPOSED leader's late RUN_JOB (epoch 3)
            conn.sendall((json.dumps(
                {"cmd": "RUN_JOB", "conf": {"job_id": "stale-job"},
                 "att": 0, "executor_ids": [], "leader_epoch": 3})
                + "\n").encode())
            while True:
                line = f.readline()
                if not line:
                    return
                msg = json.loads(line)
                if msg.get("cmd") == "JOB_DONE":
                    box["done"] = msg
                    break
            conn.sendall((json.dumps({"cmd": "SHUTDOWN"}) + "\n").encode())

        t = threading.Thread(target=leader_side, daemon=True)
        t.start()
        follower = PodFollower("127.0.0.1", port, pid=1, num_executors=1,
                               reconnect=False)
        ft = threading.Thread(target=follower.run, daemon=True)
        ft.start()
        _wait_for(lambda: "done" in box, msg="stale RUN_JOB rejection")
        done = box["done"]
        assert done["ok"] is False and done.get("stale_epoch") is True
        assert done["job_id"] == "stale-job"
        assert follower.stale_rejected == 1
        assert follower._leader_epoch == 5
        ft.join(timeout=30)
        t.join(timeout=10)
        srv.close()


class TestFollowerReHello:
    def test_follower_reconnects_on_leader_loss(self):
        """Leader change with HA on: a follower whose control socket
        EOFs re-HELLOs the (new) leader under the same pid instead of
        shutting down — executors and entities survive the window."""
        from harmony_tpu.jobserver.pod import PodFollower

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(2)
        port = srv.getsockname()[1]
        joins = []

        def leader_side():
            # first leader: accept the JOIN, then DIE (close the socket
            # AND its makefile — the file object holds the fd, and only
            # the last close sends the FIN the follower's EOF needs)
            conn, _ = srv.accept()
            f = conn.makefile("r")
            joins.append(json.loads(f.readline()))
            f.close()
            conn.close()
            # successor on the SAME port: the follower must re-JOIN it
            conn2, _ = srv.accept()
            f2 = conn2.makefile("r")
            joins.append(json.loads(f2.readline()))
            conn2.sendall((json.dumps({"cmd": "SHUTDOWN"}) + "\n")
                          .encode())

        t = threading.Thread(target=leader_side, daemon=True)
        t.start()
        follower = PodFollower("127.0.0.1", port, pid=3, num_executors=1,
                               reconnect=True)
        ft = threading.Thread(target=follower.run, daemon=True)
        ft.start()
        ft.join(timeout=60)
        assert not ft.is_alive(), "follower never saw the SHUTDOWN"
        t.join(timeout=10)
        assert [j["cmd"] for j in joins] == ["JOIN", "JOIN"]
        assert [j["pid"] for j in joins] == [3, 3]  # SAME identity kept
        srv.close()


class TestRearmPolicy:
    def test_rearm_branches(self, tmp_path, monkeypatch):
        """Takeover re-arm policy: elastic jobs continue their attempt
        sequence, chained jobs resume_from_chain, chainless ones re-run
        raw — and one failing re-arm never blocks the rest."""
        from harmony_tpu.jobserver.ha import HAController

        def conf(job_id, **user):
            cfg = _laggy_job(job_id, 1, lag=0.0)
            cfg.user.update(user)
            return cfg.to_dict()  # what the submission entry carries

        st = ReplayState.from_entries([
            {"seq": 1, "epoch": 1, "kind": "submission", "job": "el",
             "config": conf("el", elastic_shrink=True)},
            {"seq": 2, "epoch": 1, "kind": "dispatch", "job": "el",
             "attempt": 1},
            {"seq": 3, "epoch": 1, "kind": "submission", "job": "ch",
             "config": conf("ch")},
            {"seq": 4, "epoch": 1, "kind": "submission", "job": "raw",
             "config": conf("raw")},
            {"seq": 5, "epoch": 1, "kind": "submission", "job": "boom",
             "config": conf("boom")},
        ])

        class FakeServer:
            _chkp_root = str(tmp_path)

            def __init__(self):
                self.submitted = []

            def submit(self, cfg):
                if cfg.job_id == "boom":
                    raise RuntimeError("synthetic re-arm failure")
                self.submitted.append(cfg)

        monkeypatch.setattr(
            HAController, "_has_chain",
            staticmethod(lambda server, job: job in ("el", "ch")))
        ctl = HAController.__new__(HAController)  # policy only, no I/O
        server = FakeServer()
        rearmed = HAController._rearm(ctl, server, st)
        assert rearmed == ["el", "ch", "raw"]  # boom failed, rest ran
        by_id = {c.job_id: c for c in server.submitted}
        rec = by_id["el"].user["elastic_recovery"]
        assert rec["attempt"] == 2 and rec["kind"] == "shrink"
        assert by_id["ch"].user.get("resume_from_chain") is True
        assert "resume_from_chain" not in by_id["raw"].user
        assert "elastic_recovery" not in by_id["raw"].user


# -- in-process takeover -----------------------------------------------------


def _laggy_job(job_id: str, epochs: int, lag: float = 0.25):
    return JobConfig(
        job_id=job_id, app_type="dolphin",
        trainer="tests.helpers:LaggyMLRTrainer",
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=2,
            app_params={"num_classes": 4, "num_features": 16,
                        "features_per_partition": 4, "step_size": 0.1,
                        "lag_sec": lag, "lag_worker": "/w0"},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": 64, "num_features": 16,
                            "num_classes": 4, "seed": 11}},
    )


class TestTakeover:
    """Deliberate default-tier sentinel (like the pod smoke): a leader
    loss must not be able to regress green — the full real-process
    chaos version with loss parity is tests/test_ha_pod.py."""

    def test_takeover_rearms_in_flight_submission(self, tmp_path):
        from harmony_tpu.jobserver.client import CommandSender
        from harmony_tpu.jobserver.ha import HAController
        from harmony_tpu.jobserver.server import JobServer

        joblog.clear_events()
        ha_dir = str(tmp_path / "ha")
        EPOCHS = 4

        def factory():
            return JobServer(num_executors=2)

        a = HAController(factory, log_dir=ha_dir, replica_id="rep-a",
                         submit_port=0, lease_s=0.6).start()
        assert a.wait_leader(30), "first replica must take the lease"
        assert a.lease.epoch == 1
        a_addr = f"127.0.0.1:{a.port}"
        cfg = _laggy_job("ha-victim", EPOCHS)
        sender = CommandSender(addrs=[a_addr])
        resp = sender.send_job_submit_command(cfg)
        assert resp.get("ok"), resp
        # CRASH the leader mid-job: TCP gone, renewals stop, lease
        # lapses — but the process lives on (the in-process analogue of
        # a partitioned leader; the real kill is the slow test). Its
        # still-running dispatch must NOT be able to write job_done:
        # the deposed guard drops the append (split-brain fencing).
        a.server._stop_tcp()
        a.lease.stop()
        b = HAController(factory, log_dir=ha_dir, replica_id="rep-b",
                         submit_port=0, lease_s=0.6).start()
        b_addr_early = f"127.0.0.1:{b.port}"
        # while standing by, B answers NOT_LEADER to submits
        with pytest.raises(Exception):
            CommandSender(b.port).send_job_submit_command(
                _laggy_job("other", 1))
        assert b.wait_leader(30), "standby must take over after the lease"
        assert b.lease.epoch == 2
        # the submission was re-armed under the SAME job id and the
        # client reaches the result through failover (A refuses, B is
        # tried next; the port did not move — the standby endpoint
        # vacated it for the real server)
        assert f"127.0.0.1:{b.port}" == b_addr_early
        failover = CommandSender(addrs=[a_addr, f"127.0.0.1:{b.port}"])
        result = failover.wait_result("ha-victim", timeout=120)
        (w,) = result["workers"].values()
        assert len(w["losses"]) + int(w["starting_epoch"]) == EPOCHS
        # takeover evidence: one structured leader_takeover event with
        # the re-armed submission, riding STATUS's ha section
        status = CommandSender(b.port).send_status_command()
        ha = status["ha"]
        assert ha["enabled"] and ha["role"] == "leader"
        assert ha["leader_epoch"] == 2
        # first election (rep-a, no predecessor) + the real takeover
        tk = ha["takeovers"][-1]
        assert tk["old_leader"] == "rep-a"
        assert tk["new_leader"] == "rep-b"
        assert tk["rearmed"] == ["ha-victim"]
        assert tk["replay_ms"] > 0
        # fencing held: the log's job_done for the victim (if any) was
        # written by epoch 2, never by the deposed epoch-1 leader
        st = ReplayState.from_entries(b.server.ha_log.entries())
        done = st.done.get("ha-victim")
        if done is not None:
            assert int(done["epoch"]) >= 2
        b.stop()
        a.stop()
        joblog.clear_events()


# -- standby endpoint / client redirect -------------------------------------


class TestDurableSink:
    def test_event_fields_never_clash_with_envelope(self, tmp_path):
        """Regression: joblog events carrying envelope-named fields
        (elastic fences carry their own ``epoch``, diagnoses a ``job``)
        must land in the durable log — namespaced ``ev_*`` — instead of
        raising inside the sink and silently vanishing from the very
        history a takeover replays."""
        from harmony_tpu.jobserver.server import JobServer

        server = JobServer(num_executors=1)
        log = DurableJobLog(str(tmp_path / "job.walog"))
        try:
            server.enable_ha(log)
            joblog.record_event("j1", "elastic_shrink_fence",
                                epoch=7, attempt=2)
            joblog.record_event("j1", "diagnosis", job="j1",
                                rule="straggler")
            entries = log.entries()
            kinds = [e["kind"] for e in entries]
            assert kinds == ["elastic_shrink_fence", "diagnosis"], kinds
            fence = entries[0]
            assert fence["ev_epoch"] == 7       # the event's own epoch
            assert fence["epoch"] == 0          # the LEADER epoch
            assert fence["attempt"] == 2        # non-reserved untouched
            assert entries[1]["ev_job"] == "j1"
        finally:
            server._stop_ha()
            joblog.clear_events()


class TestNotLeaderRedirect:
    def test_standby_redirects_to_leader(self):
        from harmony_tpu.jobserver.client import (
            CommandSender,
            NotLeaderError,
        )
        from harmony_tpu.jobserver.ha import StandbyEndpoint
        from harmony_tpu.jobserver.server import JobServer

        leader = JobServer(num_executors=1)
        leader.start()
        lport = leader.serve_tcp(0)
        standby = StandbyEndpoint(
            0, info_fn=lambda: {"role": "standby"},
            leader_hint_fn=lambda: f"127.0.0.1:{lport}")
        sport = standby.start()
        try:
            # STATUS passes through on a standby (operators can look)
            st = CommandSender(sport).send_status_command()
            assert st["state"] == "STANDBY" and st["ok"]
            # a raw submit against the standby is NOT_LEADER...
            with pytest.raises(NotLeaderError) as ei:
                CommandSender(sport).send_job_submit_command(
                    _laggy_job("redir", 1, lag=0.0))
            assert ei.value.leader == f"127.0.0.1:{lport}"
            # ...and the failover client follows the redirect hint
            sender = CommandSender(addrs=[f"127.0.0.1:{sport}"])
            resp = sender.send_job_submit_command(
                _laggy_job("redir", 1, lag=0.0))
            assert resp.get("ok"), resp
            assert sender._leader_hint == f"127.0.0.1:{lport}"
            result = sender.wait_result("redir", timeout=60)
            assert result["workers"]
        finally:
            standby.stop()
            leader.shutdown(timeout=60)


class TestObsEndpointResolution:
    def test_resolve_learns_addr_list(self, monkeypatch):
        import argparse

        from harmony_tpu.cli import _resolve_obs_endpoint

        ns = argparse.Namespace(what="doctor", port=None, url=None)
        monkeypatch.setenv("HARMONY_JOBSERVER_ADDRS",
                           "10.0.0.1:43110, 10.0.0.2:43110")
        kind, endpoint = _resolve_obs_endpoint(ns)
        assert kind == "addrs"
        assert endpoint == ["10.0.0.1:43110", "10.0.0.2:43110"]
        # the explicit flag still wins
        ns2 = argparse.Namespace(what="doctor", port=7777, url=None)
        assert _resolve_obs_endpoint(ns2) == ("port", 7777)
        # without the list, the old port resolution is unchanged
        monkeypatch.delenv("HARMONY_JOBSERVER_ADDRS")
        monkeypatch.setenv("HARMONY_JOBSERVER_PORT", "4242")
        assert _resolve_obs_endpoint(ns) == ("port", 4242)
