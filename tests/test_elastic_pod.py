"""Elastic shrink-to-survivors on REAL multi-process pods (slow tier).

The acceptance bar for the elastic recovery path, driven by the PR-2
deterministic fault harness (no kill races, no polling):

  * a follower KILLED at an exact mid-epoch step on a
    ``user.elastic_shrink`` job -> the SAME submission completes on the
    survivor set (no resubmit, the client future never fails), with
    final-loss parity against an uninterrupted run;
  * a follower going MUTE (bounded heartbeat silence) on a job spanning
    leader+follower -> lockstep shrink fence, partial restore whose
    checkpoint reads are exactly the LOST blocks (O(lost bytes),
    asserted against the restore accounting), then — when its beats
    resume — automatic re-grow back to the original executor layout,
    every batch still processed exactly once per epoch.
"""
import json

import pytest

from harmony_tpu import faults

pytestmark = [pytest.mark.slow, pytest.mark.faults]


def _elastic_cfg(job_id: str, epochs: int, lag: float = 0.0, seed: int = 31):
    from harmony_tpu.config.params import JobConfig, TrainerParams

    app = {"num_classes": 4, "num_features": 16,
           "features_per_partition": 4, "step_size": 0.1}
    trainer = "harmony_tpu.apps.mlr:MLRTrainer"
    if lag:
        trainer = "tests.helpers:LaggyMLRTrainer"
        app = dict(app, lag_sec=lag, lag_worker="/w0")
    return JobConfig(
        job_id=job_id, app_type="dolphin", trainer=trainer,
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=2, model_chkp_period=1,
            app_params=app,
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": 64, "num_features": 16,
                            "num_classes": 4, "seed": seed},
              "elastic_shrink": True},
    )


def _uninterrupted_final_loss(cfg, epochs):
    from harmony_tpu.jobserver.server import JobServer

    server = JobServer(num_executors=2)
    server.start()
    try:
        base = type(cfg).from_dict(cfg.to_dict())
        base.user.pop("elastic_shrink", None)
        base.trainer = "harmony_tpu.apps.mlr:MLRTrainer"
        base.params.app_params = {
            k: v for k, v in base.params.app_params.items()
            if not k.startswith("lag_")
        }
        res = server.submit(base).result(timeout=300)
        (losses,) = [w["losses"] for w in res["workers"].values()]
        assert len(losses) == epochs
        return float(losses[-1])
    finally:
        server.shutdown(timeout=60)


def test_injected_follower_kill_elastic_shrink_same_submission(tmp_path):
    """Acceptance leg 1: the follower hosting the whole carved victim is
    crashed at its 21st worker step. Unlike auto_resume (PR 2), the
    submission is NEVER resubmitted — the elastic loop re-dispatches it
    in place onto the surviving process, restoring the last committed
    chain entry (all blocks lost with the follower -> every needed block
    read back, CRC-verified), and the one future completes with loss
    parity against an uninterrupted run."""
    from tests.test_multihost import PodHarness, _mlr_job

    EPOCHS = 24
    plan = faults.FaultPlan([faults.FaultRule(
        "worker.step", match={"proc": 1}, after=20, count=1,
        action="crash", exit_code=86,
    )])
    pod = PodHarness(2, 2, scheduler="pod_carve:1",
                     env_extra={"HARMONY_POD_CHKP_ROOT": str(tmp_path),
                                "HARMONY_POD_HB_TIMEOUT": "5",
                                "HARMONY_POD_HB_PERIOD": "0.5",
                                faults.ENV_VAR: plan.to_json()})
    try:
        pod.wait_ready()
        # filler takes the leader's carve first so the victim lands
        # wholly on the follower (the process the plan targets)
        filler = _mlr_job("ek-filler", seed=1, epochs=1)
        filler.params.num_mini_batches = 2
        victim = _elastic_cfg("ek-victim", EPOCHS)
        for cfg in (filler, victim):
            resp = pod.sender.send_job_submit_command(cfg)
            assert resp.get("ok"), resp
        pod.drain(timeout=300)
        pod.sender.send_shutdown_command()
        out, err = pod.procs[0].communicate(timeout=120)
        lead = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lead, (out, err[-2000:])
        result = json.loads(lead[0][len("RESULT "):])
        # the follower died OF THE INJECTION (its exit code), not a kill
        assert pod.procs[1].wait(timeout=60) == 86
    finally:
        pod.kill()
    vres = result["local_results"]["ek-victim"]
    assert "error" not in vres, vres
    # SAME submission: nothing was resubmitted through the auto-resume
    # path, and the elastic metadata shows exactly one in-place recovery
    assert result["auto_resumed"] == []
    assert vres["elastic"]["attempts"] == 2
    assert [e["kind"] for e in vres["elastic"]["events"]] == \
        ["elastic_shrink"]
    # the recovery ran on the LEADER's process (the only survivor)
    assert vres["elastic"]["events"][0]["procs"] == [0]
    # restore accounting: the dead follower held EVERY block of the
    # carved job, so lost == needed and all of them were read back
    rst = vres["elastic_restore"]
    assert rst["partial"] == 1 and rst["kind"] == "shrink"
    assert rst["blocks_read"] == rst["blocks_needed"] > 0
    assert rst["blocks_local"] == 0
    assert rst["lost_block_count"] == rst["blocks_read"]
    # only the remaining epochs ran after the crash point's floor
    (w,) = [v for v in vres.values()
            if isinstance(v, dict) and "losses" in v]
    assert w["starting_epoch"] == rst["resumed_epoch"] > 0
    assert w["epochs_run"] == EPOCHS - rst["resumed_epoch"]
    # loss parity with an uninterrupted run of the same config
    ref = _uninterrupted_final_loss(_elastic_cfg("ek-ref", EPOCHS), EPOCHS)
    assert round(float(w["losses"][-1]), 5) == round(ref, 5)


def test_injected_silence_shrinks_then_regrows_to_original(tmp_path):
    """Acceptance leg 2: the follower hosting the carved victim goes
    MUTE for a bounded window (the partial failure a kill cannot test —
    its process keeps training, only the beacon stops). The monitor
    confines it; the SAME submission shrinks onto the leader (infra-
    classified, restore from the last committed chain entry) while a
    lockstep shrink fence cleanly tears down the mute side's stale
    attempt. When the beats resume, the follower is rehabilitated and a
    re-grow fence moves the job BACK to its original executor layout,
    where it completes — one future end to end, loss parity against an
    uninterrupted run, and the final attempt's epoch range tiling the
    tail exactly (every batch once per epoch in the effective history).

    (The leader-holds-half O(lost-bytes) cache split needs cross-process
    SPMD meshes, which this host's jax CPU backend refuses — the exact
    read accounting for that shape is pinned in
    tests/test_elastic.py::TestPartialRestore instead.)"""
    from tests.test_multihost import PodHarness, _mlr_job

    EPOCHS = 100  # generous tail: the re-grow fence needs floor+horizon
    #               headroom AFTER the beats resume mid-shrunk-attempt
    plan = faults.FaultPlan([faults.FaultRule(
        "pod.heartbeat", match={"pid": 1}, after=6, count=30,
        action="skip",
    )])
    pod = PodHarness(2, 2, scheduler="pod_carve:1",
                     env_extra={"HARMONY_POD_CHKP_ROOT": str(tmp_path),
                                "HARMONY_POD_HB_TIMEOUT": "3",
                                "HARMONY_POD_HB_PERIOD": "0.5",
                                faults.ENV_VAR: plan.to_json()})
    try:
        pod.wait_ready()
        filler = _mlr_job("es-filler", seed=1, epochs=1)
        filler.params.num_mini_batches = 2
        victim = _elastic_cfg("es-victim", EPOCHS, lag=0.3)
        for cfg in (filler, victim):
            resp = pod.sender.send_job_submit_command(cfg)
            assert resp.get("ok"), resp
        pod.drain(timeout=600)
        result = pod.finish(timeout=240)
    finally:
        pod.kill()
    vres = result["local_results"]["es-victim"]
    assert "error" not in vres, vres
    assert result["auto_resumed"] == []  # SAME submission throughout
    meta = vres["elastic"]
    kinds = [e["kind"] for e in meta["events"]]
    assert kinds == ["elastic_shrink", "elastic_regrow"], (kinds, meta)
    assert meta["attempts"] == 3
    shrink_ev, regrow_ev = meta["events"]
    # shrink moved the job to the leader; the re-grow returned it to the
    # ORIGINAL executor layout on the rehabilitated follower
    assert shrink_ev["procs"] == [0]
    assert regrow_ev["procs"] == [1]
    assert sorted(regrow_ev["executors"]) == sorted(
        shrink_ev["lost_executors"])
    # pod-level recovery events: the full confine -> shrink ->
    # rehabilitate -> re-grow arc was observed
    pod_kinds = [e["kind"] for e in result["elastic_events"]]
    for k in ("follower_silenced", "elastic_shrink_fence",
              "follower_rehabilitated", "elastic_regrow_fence",
              "elastic_shrink", "elastic_regrow"):
        assert k in pod_kinds, (k, pod_kinds)
    # restore accounting, one event per recovery (the structured log
    # keeps every attempt's accounting, not just the last one's): the
    # shrink lost everything (the victim lived wholly on the mute
    # follower) and read it all back, CRC-verified
    # (the leader's log holds the shrink restore — attempt 1 ran there;
    # the regrow attempt ran wholly on the follower, whose restore
    # accounting rides the chief's result instead)
    (shrink_rst,) = [e for e in result["job_events"].get("es-victim", [])
                     if e["kind"] == "elastic_restore"]
    assert shrink_rst["recovery"] == "shrink"
    assert shrink_rst["blocks_read"] == shrink_rst["blocks_needed"] > 0
    assert shrink_rst["lost_block_count"] == shrink_rst["blocks_read"]
    regrow_rst = vres["elastic_restore"]
    assert regrow_rst["kind"] == "regrow"
    assert regrow_rst["attempt"] == 2
    # the re-grow fence is the recovery point of the final attempt
    fences = {e["kind"]: e["epoch"] for e in result["elastic_events"]
              if e["kind"].endswith("_fence")}
    assert regrow_rst["resumed_epoch"] == fences["elastic_regrow_fence"] + 1
    assert 0 < shrink_rst["resumed_epoch"] < regrow_rst["resumed_epoch"]
    # exactly-once in the effective history: the final attempt covers
    # precisely the tail; earlier epochs came from exactly one committed
    # lineage (parity below is the numeric proof)
    (w,) = [v for v in vres.values()
            if isinstance(v, dict) and "losses" in v]
    assert w["starting_epoch"] == regrow_rst["resumed_epoch"]
    assert w["epochs_run"] == EPOCHS - w["starting_epoch"]
    # the final attempt really ran on the follower again: its report for
    # the submission's last attempt matches the result series
    frep = result["pod_reports"]["es-victim"]["1"]
    assert frep["ok"], frep
    fw = frep["workers"]["es-victim/w0"]
    assert fw["starting_epoch"] == w["starting_epoch"]
    assert [round(x, 5) for x in fw["losses"]] == [
        round(x, 5) for x in w["losses"]]
    # loss parity with an uninterrupted run of the same config
    ref = _uninterrupted_final_loss(_elastic_cfg("es-ref", EPOCHS), EPOCHS)
    assert abs(float(w["losses"][-1]) - ref) < 1e-5, (w["losses"][-1], ref)
