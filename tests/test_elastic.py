"""Elastic shrink-to-survivors recovery — the fast tier.

Covers every layer of the in-place recovery path without spawning pod
processes: the shrink-plan helpers (table/ownership.py), the partial
restore + recovery cache + read accounting (checkpoint/manager.py), the
leader's elastic dispatch loop end-to-end in-process (fence -> same
submission recovers -> loss parity), the silence-confine/rehabilitate
monitor and replacement-JOIN reinstatement against fake follower
sockets, the scheduler's reacquire/restore surface, and recovery chaos
at the new fault sites. Real multi-process pods: tests/test_elastic_pod.py
(slow tier)."""
import json
import socket
import threading
import time

import numpy as np
import pytest

from harmony_tpu import faults
from harmony_tpu.checkpoint import manager as chkp_manager
from harmony_tpu.checkpoint.manager import CheckpointManager
from harmony_tpu.config.params import JobConfig, TableConfig, TrainerParams
from harmony_tpu.jobserver import elastic
from harmony_tpu.jobserver.elastic import ElasticFence
from harmony_tpu.parallel import DevicePool
from harmony_tpu.runtime import ETMaster
from harmony_tpu.table import ownership


@pytest.fixture(autouse=True)
def _clean_state():
    chkp_manager.reset_read_stats()
    chkp_manager.drop_recovery_cache()
    yield
    faults.disarm()
    chkp_manager.drop_recovery_cache()


# -- ownership shrink plans ----------------------------------------------


class TestShrinkPlan:
    def test_lost_blocks_from_manifest_vector(self):
        # 8 blocks round-robined over a,b,c; c dies
        own = [i % 3 for i in range(8)]
        execs = ["a", "b", "c"]
        assert ownership.lost_blocks(own, execs, ["c"]) == [2, 5]
        assert ownership.lost_blocks(own, execs, ["a", "c"]) == [0, 2, 3, 5, 6]
        assert ownership.lost_blocks(own, execs, ["zz"]) == []

    def test_shrink_plan_spreads_lost_evenly(self):
        own = [i % 4 for i in range(16)]
        execs = ["a", "b", "c", "d"]
        plan = ownership.shrink_plan(own, execs, ["d"], ["a", "b", "c"])
        assert plan["lost"] == [3, 7, 11, 15]
        sizes = sorted(len(v) for v in plan["absorbed"].values())
        assert sizes == [1, 1, 2]  # differs by at most one block
        assert sorted(b for v in plan["absorbed"].values() for b in v) == \
            plan["lost"]

    def test_shrink_plan_needs_a_survivor(self):
        with pytest.raises(ValueError, match="survivor"):
            ownership.shrink_plan([0], ["a"], ["a"], [])


# -- partial restore + recovery cache ------------------------------------


def _make_handle(master, tid, capacity=64, vshape=(2,), n_exec=4):
    exs = master.add_executors(n_exec)
    cfg = TableConfig(table_id=tid, capacity=capacity, value_shape=vshape,
                      num_blocks=16)
    h = master.create_table(cfg, [e.id for e in exs])
    vals = np.arange(capacity, dtype=np.float32)[:, None] * np.ones(
        vshape, np.float32)
    h.table.multi_update(list(range(capacity)), vals)
    return h, vals


class TestPartialRestore:
    @pytest.fixture()
    def master(self, devices):
        return ETMaster(DevicePool(devices))

    @pytest.fixture()
    def mgr(self, tmp_path):
        return CheckpointManager(str(tmp_path / "t"), str(tmp_path / "c"))

    def test_cold_restore_reads_every_block_and_counts(self, mgr, master):
        h, vals = _make_handle(master, "pr-cold")
        cid = mgr.checkpoint(h, commit=True)
        chkp_manager.reset_read_stats()
        h2, stats = mgr.restore_partial(master, cid,
                                        master.executor_ids()[:2],
                                        table_id="pr-cold2")
        np.testing.assert_allclose(np.asarray(h2.table.pull_array()), vals)
        assert stats["partial"] == 1
        assert stats["blocks_read"] == 16 and stats["blocks_local"] == 0
        assert chkp_manager.read_stats["blocks_read"] == 16
        assert stats["bytes_read"] == chkp_manager.read_stats["bytes_read"] > 0

    def test_recovery_cache_makes_restore_read_nothing(self, mgr, master):
        mgr.recovery_retain = True
        h, vals = _make_handle(master, "pr-warm")
        cid = mgr.checkpoint(h, commit=True)
        assert chkp_manager.recovery_blocks(cid) is not None
        chkp_manager.reset_read_stats()
        h2, stats = mgr.restore_partial(master, cid,
                                        master.executor_ids()[:2],
                                        table_id="pr-warm2")
        np.testing.assert_allclose(np.asarray(h2.table.pull_array()), vals)
        assert stats["blocks_read"] == 0 and stats["blocks_local"] == 16
        assert chkp_manager.read_stats["blocks_read"] == 0

    def test_partial_cache_split_reads_exactly_the_lost_half(self, mgr,
                                                             master):
        """The pod shape of the O(lost-bytes) contract: a process whose
        recovery cache holds only ITS addressable half (what the pod
        checkpoint stages per process) reads back from storage exactly
        the other half — the blocks that died with the peer."""
        h, vals = _make_handle(master, "pr-half")
        cid = mgr.checkpoint(h, commit=True)
        mine = {b: np.asarray(h.table.export_blocks([b])[b])
                for b in range(8)}  # this process staged blocks 0..7
        chkp_manager._recovery_put("pr-half", cid, mine)
        chkp_manager.reset_read_stats()
        h2, stats = mgr.restore_partial(master, cid,
                                        master.executor_ids()[:2],
                                        table_id="pr-half2")
        np.testing.assert_allclose(np.asarray(h2.table.pull_array()), vals)
        assert stats["blocks_local"] == 8
        assert stats["blocks_read"] == 8  # exactly the lost half
        assert chkp_manager.read_stats["blocks_read"] == 8

    def test_stale_cache_entry_is_never_used(self, mgr, master):
        """The cache keys by EXACT checkpoint id: an older entry of the
        same table must not leak a stale epoch into a recovery (the
        consistent-cut guarantee)."""
        mgr.recovery_retain = True
        h, _ = _make_handle(master, "pr-stale")
        cid1 = mgr.checkpoint(h, commit=True)
        h.table.multi_update([0], np.full((1, 2), 99.0, np.float32))
        cid2 = mgr.checkpoint(h, commit=True)
        assert chkp_manager.recovery_blocks(cid1) is None  # superseded
        assert chkp_manager.recovery_blocks(cid2) is not None
        chkp_manager.reset_read_stats()
        h2, stats = mgr.restore_partial(master, cid1,
                                        master.executor_ids()[:2],
                                        table_id="pr-stale2")
        assert stats["blocks_read"] == 16  # cid1 must be re-read in full
        assert np.asarray(h2.table.pull_array())[0, 0] == 0.0

    def test_partial_restore_verifies_crc(self, mgr, master, tmp_path):
        import os

        h, _ = _make_handle(master, "pr-crc")
        cid = mgr.checkpoint(h)  # temp stage: block files live here
        d = os.path.join(mgr.temp_root, cid)
        (blk,) = [n for n in sorted(os.listdir(d)) if n.startswith("3.")]
        path = os.path.join(d, blk)
        with open(path, "r+b") as f:
            f.seek(0)
            f.write(b"\xff\xff\xff\xff")
        with pytest.raises(chkp_manager.CheckpointCorruptError):
            mgr.restore_partial(master, cid, master.executor_ids()[:2],
                                table_id="pr-crc2")
        # no half-restored orphan table left behind
        assert "pr-crc2" not in master.table_ids()

    def test_sparse_falls_back_to_full_restore(self, mgr, master):
        exs = master.add_executors(2)
        cfg = TableConfig(table_id="pr-sparse", capacity=64, value_shape=(2,),
                          num_blocks=4, sparse=True)
        h = master.create_table(cfg, [e.id for e in exs])
        h.table.multi_update([3, 9], np.ones((2, 2), np.float32))
        cid = mgr.checkpoint(h, commit=True)
        h2, stats = mgr.restore_partial(master, cid,
                                        [e.id for e in exs][:1],
                                        table_id="pr-sparse2")
        assert stats["partial"] == 0
        np.testing.assert_allclose(
            np.asarray(h2.table.multi_get([3, 9])), 1.0)


# -- the elastic dispatch loop, in-process -------------------------------


def _elastic_cfg(job_id, epochs, seed=3, extra_user=None):
    user = {"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
            "data_args": {"n": 64, "num_features": 16, "num_classes": 4,
                          "seed": seed},
            "elastic_shrink": True}
    user.update(extra_user or {})
    return JobConfig(
        job_id=job_id, app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=2, model_chkp_period=1,
            app_params={"num_classes": 4, "num_features": 16,
                        "features_per_partition": 4, "step_size": 0.1},
        ),
        num_workers=1,
        user=user,
    )


@pytest.fixture()
def pod_server(tmp_path):
    from harmony_tpu.jobserver.pod import PodJobServer

    srv = PodJobServer(num_executors=2, num_followers=0,
                       chkp_root=str(tmp_path / "chkp"))
    srv.start()
    srv.serve_pod(0)
    yield srv
    srv.shutdown(timeout=120)


def _fence_when_active(srv, job_id, kind, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with srv._pod_cond:
            live = job_id in srv._elastic_active
        if live:
            ep = srv._schedule_elastic_fence(job_id, kind)
            assert ep is not None, "fence refused (job too short?)"
            return ep
        time.sleep(0.02)
    raise AssertionError("job never became elastic-active")


EPOCHS = 16


class TestElasticDispatchLoop:
    def test_fence_recovers_same_submission_with_parity(self, pod_server):
        """The tentpole, in one process: a shrink fence tears the attempt
        down at a lockstep epoch; the SAME submission (same future, no
        resubmit) resumes one epoch later from the recovery cache
        (0 checkpoint block reads) and lands numerically exactly where an
        uninterrupted run lands."""
        fut = pod_server.submit(_elastic_cfg("el-fence", EPOCHS))
        fence_ep = _fence_when_active(pod_server, "el-fence", "shrink")
        res = fut.result(timeout=180)
        meta = res["elastic"]
        assert meta["attempts"] == 2 and meta["recoveries"] == 1
        assert [e["kind"] for e in meta["events"]] == ["elastic_shrink"]
        rst = res["elastic_restore"]
        assert rst["partial"] == 1
        assert rst["resumed_epoch"] == fence_ep + 1
        assert rst["blocks_read"] == 0  # all blocks from the recovery cache
        assert rst["blocks_local"] == rst["blocks_needed"] > 0
        # exactly-once: the final attempt covers exactly the tail epochs
        (w,) = res["workers"].values()
        assert w["starting_epoch"] == fence_ep + 1
        assert w["epochs_run"] == EPOCHS - (fence_ep + 1)
        # loss parity with an uninterrupted run of the same config
        from harmony_tpu.jobserver.server import JobServer

        ref = JobServer(num_executors=2)
        ref.start()
        try:
            base = _elastic_cfg("el-ref", EPOCHS)
            base.user.pop("elastic_shrink")
            r2 = ref.submit(base).result(timeout=180)
            (w2,) = r2["workers"].values()
            assert round(w["losses"][-1], 6) == round(w2["losses"][-1], 6)
        finally:
            ref.shutdown(timeout=60)
        # observability: status carries the recovery events
        status = pod_server._status()
        kinds = [e["kind"] for e in status["elastic"]["events"]]
        assert "elastic_shrink_fence" in kinds and "elastic_shrink" in kinds
        assert "fault_counters" in status and "job_events" in status
        assert any(ev["kind"] == "elastic_restore"
                   for ev in status["job_events"].get("el-fence", []))

    def test_own_terms_failure_is_never_recovered(self, pod_server):
        cfg = _elastic_cfg("el-bug", 4)
        cfg.user["data_args"] = {"n": 1, "num_features": 16,
                                 "num_classes": 4, "seed": 1}  # too few
        with pytest.raises(Exception, match="cannot feed"):
            pod_server.submit(cfg).result(timeout=120)
        ev = [e for e in pod_server.elastic_events
              if e.get("job_id") == "el-bug"]
        assert [e["kind"] for e in ev] == ["elastic_give_up"]
        assert "own terms" in ev[0]["reason"]

    def test_recovery_cap_bounds_fence_loops(self, pod_server, monkeypatch):
        monkeypatch.setenv("HARMONY_ELASTIC_MAX_SHRINKS", "0")
        fut = pod_server.submit(_elastic_cfg("el-cap", EPOCHS))
        _fence_when_active(pod_server, "el-cap", "shrink")
        with pytest.raises(ElasticFence):
            fut.result(timeout=120)

    def test_injected_planning_death_fails_loudly(self, pod_server):
        """Chaos: death-during-shrink (the pod.shrink_plan site). The
        recovery planner dying must fail the submission with the
        original fence error — loudly, promptly, no hang, no retry
        loop."""
        faults.arm(faults.FaultPlan([faults.FaultRule(
            "pod.shrink_plan", count=1, exc="RuntimeError",
            message="planner struck down",
        )]))
        fut = pod_server.submit(_elastic_cfg("el-plandeath", EPOCHS))
        _fence_when_active(pod_server, "el-plandeath", "shrink")
        with pytest.raises(ElasticFence):
            fut.result(timeout=120)
        assert any(e["kind"] == "elastic_give_up"
                   and "planning failed" in e.get("reason", "")
                   for e in pod_server.elastic_events)

    def test_injected_restore_failure_fails_loudly(self, pod_server,
                                                   monkeypatch):
        """Chaos: a second failure MID-RESTORE (the chkp.partial_read
        site, standing in for a second follower dying while its blocks
        are read back). The recovery attempt fails; the submission fails
        cleanly instead of hanging or looping."""
        monkeypatch.setenv("HARMONY_ELASTIC_CACHE", "0")  # force reads
        faults.arm(faults.FaultPlan([faults.FaultRule(
            "chkp.partial_read", count=-1, exc="OSError",
            message="second failure mid-restore",
        )]))
        fut = pod_server.submit(_elastic_cfg("el-midrestore", EPOCHS))
        _fence_when_active(pod_server, "el-midrestore", "shrink")
        with pytest.raises(OSError, match="mid-restore"):
            fut.result(timeout=120)


# -- silence monitor / rehabilitation / reinstatement ---------------------


class _FakeFollower:
    """A scripted control-plane follower: JOINs, heartbeats on demand."""

    def __init__(self, port, pid):
        self.pid = pid
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.file = self.sock.makefile("r")
        self.send({"cmd": "JOIN", "pid": pid})

    def send(self, msg):
        self.sock.sendall((json.dumps(msg) + "\n").encode())

    def heartbeat(self, jobs=()):
        self.send({"cmd": "HEARTBEAT", "pid": self.pid,
                   "jobs": list(jobs)})

    def close(self):
        # the makefile dup must close too, or the server never sees EOF
        for obj in (self.file, self.sock):
            try:
                obj.close()
            except OSError:
                pass


class TestSilenceMonitorAndReinstatement:
    def _server(self, tmp_path, n_followers=1):
        from harmony_tpu.jobserver.pod import PodJobServer

        srv = PodJobServer(num_executors=2, num_followers=n_followers,
                           chkp_root=str(tmp_path / "chkp"))
        srv.start()
        srv.hb_timeout = 1.0
        return srv

    def test_silence_confines_then_resumed_beats_rehabilitate(self, tmp_path):
        srv = self._server(tmp_path)
        port_box = {}
        t = threading.Thread(
            target=lambda: port_box.update(p=srv.serve_pod(0)), daemon=True)
        t.start()
        for _ in range(100):
            if srv._pod_sock is not None:
                break
            time.sleep(0.02)
        fake = _FakeFollower(srv._pod_sock.getsockname()[1], pid=1)
        try:
            t.join(timeout=30)
            assert not t.is_alive(), "serve_pod never completed the join"
            # beats flow: no confinement
            for _ in range(3):
                fake.heartbeat()
                time.sleep(0.2)
            assert 1 not in srv._silenced
            # silence past hb_timeout: the monitor confines
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and 1 not in srv._silenced:
                time.sleep(0.1)
            assert 1 in srv._silenced and 1 in srv._unusable_procs
            assert srv._status()["pod"]["silenced"] == [1]
            # beats resume: the monitor rehabilitates
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and 1 in srv._silenced:
                fake.heartbeat()
                time.sleep(0.1)
            assert 1 not in srv._silenced and 1 not in srv._unusable_procs
            kinds = [e["kind"] for e in srv.elastic_events]
            assert "follower_silenced" in kinds
            assert "follower_rehabilitated" in kinds
        finally:
            fake.close()
            srv.shutdown(timeout=60)

    def test_dead_follower_replacement_join_reinstates(self, tmp_path):
        srv = self._server(tmp_path)
        t = threading.Thread(target=lambda: srv.serve_pod(0), daemon=True)
        t.start()
        for _ in range(100):
            if srv._pod_sock is not None:
                break
            time.sleep(0.02)
        port = srv._pod_sock.getsockname()[1]
        fake = _FakeFollower(port, pid=1)
        try:
            t.join(timeout=30)
            assert not t.is_alive()
            fake.heartbeat()
            fake.close()  # reader EOF -> death confinement
            deadline = time.monotonic() + 15
            # the reader thread adds to _dead_followers BEFORE it runs
            # confinement + _mark_broken, so poll the broken flag too
            while (time.monotonic() < deadline
                   and not (1 in srv._dead_followers
                            and srv._status()["pod"]["broken"])):
                time.sleep(0.05)
            assert 1 in srv._dead_followers
            assert srv._status()["pod"]["broken"]
            # a REPLACEMENT process JOINs with the same pid
            fake2 = _FakeFollower(port, pid=1)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and 1 not in srv.reinstated:
                fake2.heartbeat()
                time.sleep(0.1)
            assert srv.reinstated == [1]
            assert 1 not in srv._dead_followers
            assert 1 not in srv._unusable_procs
            # the pod is whole again: the partial poison is lifted
            assert srv._status()["pod"]["broken"] is None
            fake2.close()
        finally:
            fake.close()
            srv.shutdown(timeout=60)


    def test_monitor_at_v5p32_shape_confines_only_the_silent_one(
            self, tmp_path):
        """Heartbeat tracking at the 8-follower (v5p-32) shape: seven
        healthy beacons keep beating, the eighth goes mute — ONLY the
        mute one is confined, and it rehabilitates alone when its beats
        resume."""
        srv = self._server(tmp_path, n_followers=8)
        t = threading.Thread(target=lambda: srv.serve_pod(0), daemon=True)
        t.start()
        for _ in range(100):
            if srv._pod_sock is not None:
                break
            time.sleep(0.02)
        port = srv._pod_sock.getsockname()[1]
        fakes = {pid: _FakeFollower(port, pid) for pid in range(1, 9)}
        try:
            t.join(timeout=30)
            assert not t.is_alive(), "8-follower join never completed"
            mute = 8
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and mute not in srv._silenced:
                for pid, fk in fakes.items():
                    if pid != mute:
                        fk.heartbeat()
                time.sleep(0.1)
            assert srv._status()["pod"]["silenced"] == [mute]
            assert srv._unusable_procs == {mute}  # the 7 others untouched
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and mute in srv._silenced:
                for fk in fakes.values():
                    fk.heartbeat()
                time.sleep(0.1)
            assert srv._silenced == set() and srv._unusable_procs == set()
        finally:
            for fk in fakes.values():
                fk.close()
            srv.shutdown(timeout=60)


# -- scheduler reacquire/restore -----------------------------------------


class TestSchedulerElasticSurface:
    def test_share_all_reacquire_prefers_survivors(self):
        from harmony_tpu.jobserver.scheduler import ShareAllScheduler

        s = ShareAllScheduler()
        s.bind(["e0", "e1", "e2"], lambda c, ex: None)
        s.retire(["e2"])
        assert s.reacquire("j", ["e1", "e2"]) == ["e1"]
        assert s.reacquire("j", ["e2"]) == ["e0", "e1"]  # fresh fallback
        s.restore(["e2"])
        assert s.reacquire("j", ["e2"]) == ["e2"]

    def test_carve_reacquire_takes_free_survivors_and_returns_them(self):
        from harmony_tpu.jobserver.scheduler import CarveScheduler

        s = CarveScheduler(max_share=2)
        s.bind(["e0", "e1", "e2", "e3"], lambda c, ex: None)
        got = s.reacquire("j", ["e1", "e3"])
        assert got == ["e1", "e3"]
        assert set(got) & set(s._free) == set()
        s.on_job_finish("j")  # the attempt's finish returns the slice
        assert set(s._free) == {"e0", "e1", "e2", "e3"}

    def test_process_carve_reacquire_grants_whole_processes_only(self):
        from harmony_tpu.jobserver.scheduler import ProcessCarveScheduler

        s = ProcessCarveScheduler()
        s.bind(["e0", "e1", "e2", "e3"], lambda c, ex: None)
        s.set_process_map({"e0": 0, "e1": 0, "e2": 1, "e3": 1})
        # e1 alone is half a process: must NOT be granted as a survivor
        s._free = ["e1", "e2", "e3"]
        got = s.reacquire("j", ["e1", "e2", "e3"])
        assert got == ["e2", "e3"]

    def test_restore_unblocks_queued_arrivals(self):
        from harmony_tpu.jobserver.scheduler import CarveScheduler

        launched = []
        s = CarveScheduler()
        s.bind(["e0"], lambda c, ex: launched.append((c.job_id, ex)))
        s.retire(["e0"])
        s.on_job_arrival(JobConfig(job_id="q1", app_type="dolphin"))
        assert launched == []  # queued: nothing free
        s.restore(["e0"])
        assert launched == [("q1", ["e0"])]


# -- arbiter deficit inheritance -----------------------------------------


def test_arbiter_recovery_attempt_inherits_deficit():
    from harmony_tpu.runtime.podunits import PodUnitArbiter

    arb = PodUnitArbiter(send_to=lambda p, m: None)
    arb.register_job("J", frozenset({1}))
    arb._jobs["J"].deficit = 7.5
    arb.deregister_job("J")
    # a competing tenant active at low deficit
    arb.register_job("other", frozenset({1}))
    arb._jobs["other"].deficit = 1.0
    rkey = elastic.attempt_key("J", 1)
    arb.register_job(rkey, frozenset({1}), inherit_from="J")
    assert arb._jobs[rkey].deficit == 7.5  # no fairness reset
    # without inheritance a fresh job starts at the active minimum
    arb.register_job("fresh", frozenset({1}))
    assert arb._jobs["fresh"].deficit == 1.0


def test_attempt_key_round_trip():
    assert elastic.attempt_key("j", 0) == "j"
    assert elastic.attempt_key("j", 2) == "j@a2"
    cfg = JobConfig(job_id="j", app_type="dolphin",
                    user={"elastic_recovery": {"attempt": 3}})
    assert elastic.attempt_of(cfg) == 3
    assert elastic.config_attempt_key(cfg) == "j@a3"
