#!/usr/bin/env python
"""Checkpoint I/O scaling: write / restore / partial-restore wall at
HARMONY_CHKP_IO_THREADS = 1 / 4 / 8.

Isolates the checkpoint data plane (checkpoint/manager.py) from training:
ONE dense table, full-ratio checkpoints, measured three ways —

  * write   — device snapshot D2H + per-block staging (CRC + file IO),
  * restore — full read-back into a fresh table (CRC-verified, chunked
    imports overlapping device staging with outstanding reads),
  * partial — ``restore_partial`` with HALF the blocks in the recovery
    cache, the elastic-shrink shape: only lost blocks touch storage.

Two profiles:

  * local  — this host's filesystem page cache. Pure CPU, so parallel
    gains are capped by the host's core quota (the dev sandbox measures
    a ~1.4x thread-scaling ceiling);
  * remote_5ms — a deterministic 5 ms/block latency injected at the
    chkp.block_read / chkp.block_write fault sites (delay rules, the
    HARMONY_POD_UNIT_LAT_MS precedent): the object-store/NFS profile the
    parallel data plane is FOR — storage latency overlaps across the
    I/O pool instead of summing.

Serial (threads=1) is the pre-parallel code path bit for bit; restored
arrays are asserted identical across thread counts and profiles before
any number is reported. Rounds interleave thread counts (this host's
throughput drifts), best-of per arm.

Prints ONE JSON line. Run: python benchmarks/chkp_io_bench.py
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_bench(
    num_blocks: int = 128,
    block_rows: int = 1024,
    dim: int = 256,
    threads: "tuple[int, ...]" = (1, 4, 8),
    repeats: int = 3,
    profiles: "tuple[str, ...]" = ("local", "remote_5ms"),
    tmp_root: "str | None" = None,
) -> dict:
    """Run the thread sweep per profile; returns the result dict (also
    usable from tests: tiny sizes keep it sub-second). Restores the
    ambient HARMONY_CHKP_IO_THREADS and fault plan afterwards."""
    import shutil
    import tempfile

    import numpy as np

    from harmony_tpu import faults
    from harmony_tpu.checkpoint import CheckpointManager
    from harmony_tpu.checkpoint.manager import (
        _recovery_put,
        drop_recovery_cache,
    )
    from harmony_tpu.config.params import TableConfig
    from harmony_tpu.parallel import DevicePool
    from harmony_tpu.runtime import ETMaster

    import jax

    root = tmp_root or tempfile.mkdtemp(prefix="harmony-chkp-bench-")
    prior = os.environ.get("HARMONY_CHKP_IO_THREADS")
    capacity = num_blocks * block_rows
    table_mb = capacity * dim * 4 / 1e6
    lost = None
    try:
        master = ETMaster(DevicePool(jax.devices()))
        execs = [e.id for e in
                 master.add_executors(min(4, len(jax.devices())))]
        cfg = TableConfig(table_id="chkp-bench", capacity=capacity,
                          value_shape=(dim,), num_blocks=num_blocks)
        h = master.create_table(cfg, execs)
        vals = (np.arange(capacity, dtype=np.float32)[:, None]
                % 977 * np.ones((dim,), np.float32))
        h.table.multi_update(list(range(capacity)), vals)

        # half the blocks "survive" in the recovery cache (the elastic
        # shrink shape); the other half are the lost-block storage reads
        host_blocks = {b: np.asarray(a)
                       for b, a in h.table.addressable_blocks().items()}
        cached_half = {b: a for b, a in host_blocks.items() if b % 2 == 0}
        lost = num_blocks - len(cached_half)

        reference = None
        out_profiles: dict = {}
        for profile in profiles:
            if profile == "local":
                faults.disarm()
            else:
                faults.arm(faults.FaultPlan([
                    faults.FaultRule("chkp.block_read", action="delay",
                                     delay_sec=0.005, count=-1),
                    faults.FaultRule("chkp.block_write", action="delay",
                                     delay_sec=0.005, count=-1),
                ]))
            per_thread = {str(t): {"write_s": None, "restore_s": None,
                                   "partial_restore_s": None}
                          for t in threads}
            mgrs = {t: CheckpointManager(
                os.path.join(root, f"{profile}-t{t}", "temp"),
                os.path.join(root, f"{profile}-t{t}", "commit"))
                for t in threads}
            cids: dict = {}
            run = 0
            for _ in range(repeats):
                for t in threads:
                    os.environ["HARMONY_CHKP_IO_THREADS"] = str(t)
                    mgr, row = mgrs[t], per_thread[str(t)]
                    if t in cids:
                        mgr.delete(cids[t])
                    t0 = time.perf_counter()
                    cids[t] = mgr.checkpoint(h)
                    dt = time.perf_counter() - t0
                    row["write_s"] = min(dt, row["write_s"] or dt)
                    run += 1
                    t0 = time.perf_counter()
                    rh = mgr.restore(master, cids[t], execs,
                                     table_id=f"cb-r-{profile}-{run}")
                    dt = time.perf_counter() - t0
                    row["restore_s"] = min(dt, row["restore_s"] or dt)
                    got = np.asarray(rh.table.pull_array())
                    rh.drop()
                    if reference is None:
                        reference = got
                    elif not np.array_equal(reference, got):
                        raise AssertionError(
                            f"{profile} threads={t}: restored bytes "
                            "differ from serial")
                    _recovery_put(cfg.table_id, cids[t], dict(cached_half))
                    t0 = time.perf_counter()
                    rh, stats = mgr.restore_partial(
                        master, cids[t], execs,
                        table_id=f"cb-p-{profile}-{run}")
                    dt = time.perf_counter() - t0
                    row["partial_restore_s"] = min(
                        dt, row["partial_restore_s"] or dt)
                    got = np.asarray(rh.table.pull_array())
                    rh.drop()
                    drop_recovery_cache()
                    if not np.array_equal(reference, got):
                        raise AssertionError(
                            f"{profile} threads={t}: partial restore "
                            "bytes differ")
                    if stats["blocks_read"] != lost:
                        raise AssertionError(
                            f"{profile} threads={t}: partial restore "
                            f"read {stats['blocks_read']} blocks, "
                            f"expected only the {lost} lost ones")
            for row in per_thread.values():
                for k, v in row.items():
                    row[k] = round(v, 4)
            out_profiles[profile] = per_thread
        h.drop()
    finally:
        from harmony_tpu import faults as _faults

        _faults.disarm()
        if prior is None:
            os.environ.pop("HARMONY_CHKP_IO_THREADS", None)
        else:
            os.environ["HARMONY_CHKP_IO_THREADS"] = prior
        if tmp_root is None:
            shutil.rmtree(root, ignore_errors=True)

    def speedup(profile: str, op: str) -> "float | None":
        arm = out_profiles.get(profile, {})
        serial, at4 = arm.get("1"), arm.get("4")
        if not serial or not at4:
            return None
        return round(serial[op] / at4[op], 2)

    return {
        "metric": "checkpoint block I/O scaling (write/restore/partial "
                  "restore vs HARMONY_CHKP_IO_THREADS)",
        "value": speedup("local", "restore_s"),
        "unit": "x restore speedup at 4 threads vs serial (local)",
        "table_mb": round(table_mb, 1),
        "blocks": num_blocks,
        "block_kb": round(block_rows * dim * 4 / 1024, 1),
        "lost_blocks": lost,
        "profiles": out_profiles,
        "speedups_at_4": {
            p: {op: speedup(p, f"{op}_s")
                for op in ("write", "restore", "partial_restore")}
            for p in out_profiles
        },
        "parity": "restored arrays byte-identical across thread counts "
                  "and profiles (asserted)",
        "note": "interleaved rounds, best-of-%d per arm; partial restore "
                "has half the blocks recovery-cached (only lost blocks "
                "hit storage). 'local' is page-cache I/O — pure CPU, "
                "capped by this host's ~1.4x thread-scaling ceiling; "
                "'remote_5ms' injects 5 ms/block storage latency at the "
                "chkp.block_read/chkp.block_write fault sites (the "
                "object-store profile the parallel data plane targets)"
                % repeats,
    }


def main(argv=None) -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--blocks", type=int, default=128)
    ap.add_argument("--block-rows", type=int, default=1024)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--threads", type=int, nargs="+", default=[1, 4, 8])
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--local-only", action="store_true")
    args = ap.parse_args(argv)
    res = run_bench(num_blocks=args.blocks, block_rows=args.block_rows,
                    dim=args.dim, threads=tuple(args.threads),
                    repeats=args.repeats,
                    profiles=(("local",) if args.local_only
                              else ("local", "remote_5ms")))
    print(json.dumps(res))
    return res


if __name__ == "__main__":
    main()
