#!/usr/bin/env python
"""Per-app throughput — BASELINE.md configs 1, 2, 3, 5 as single jobs.

The repo-root ``bench.py`` measures config 4 (the headline: concurrent
MLR+NMF+LDA under the multi-tenant JobServer). This file measures the
remaining BASELINE configs individually so regressions localize to an
app instead of hiding in the aggregate:

  1. MLR — single job
  2. NMF — single job
  3. LDA — single job (sparse topic-word table)
  5. Wide&Deep / FM (sparse embedding tables, keyed pulls)

One JSON line per app: {"metric", "value" (samples/sec), "unit", ...}.
Run: python benchmarks/apps.py [mlr|nmf|lda|fm|widedeep|fm-hash|all]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from harmony_tpu.utils.platform import mirror_env_platform_request  # noqa: E402

mirror_env_platform_request()  # JAX_PLATFORMS=cpu must mean cpu (axon hook)

import bench  # noqa: E402
from harmony_tpu.config.params import JobConfig, TrainerParams  # noqa: E402
from harmony_tpu.jobserver.server import JobServer  # noqa: E402
from harmony_tpu.parallel.mesh import DevicePool  # noqa: E402

EPOCHS = bench.EPOCHS
BATCHES = bench.BATCHES


def _sparse_jobs():
    fm = JobConfig(
        job_id="bench-fm", app_type="dolphin",
        trainer="harmony_tpu.apps.widedeep:FMTrainer",
        params=TrainerParams(
            num_epochs=EPOCHS, num_mini_batches=BATCHES, comm_probe_period=6,
            app_params={"vocab_size": 100_000, "num_slots": 16,
                        "emb_dim": 16, "step_size": 0.1},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.widedeep:make_synthetic",
              "data_args": {"n": 32768, "vocab_size": 100_000,
                            "num_slots": 16}},
    )
    wd = JobConfig(
        job_id="bench-widedeep", app_type="dolphin",
        trainer="harmony_tpu.apps.widedeep:WideDeepTrainer",
        params=TrainerParams(
            num_epochs=EPOCHS, num_mini_batches=BATCHES, comm_probe_period=6,
            app_params={"vocab_size": 100_000, "num_slots": 16,
                        "emb_dim": 16, "hidden": 128, "step_size": 0.1},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.widedeep:make_synthetic",
              "data_args": {"n": 32768, "vocab_size": 100_000,
                            "num_slots": 16}},
    )
    # BASELINE config 5's true "sparse embedding tables" shape: the model
    # lives in the DeviceHashTable, ids drawn from the whole int32 domain
    # (no dense preallocation possible), lazy per-key init.
    fmh = JobConfig(
        job_id="bench-fm-hash", app_type="dolphin",
        trainer="harmony_tpu.apps.widedeep:FMTrainer",
        params=TrainerParams(
            num_epochs=EPOCHS, num_mini_batches=BATCHES, comm_probe_period=6,
            app_params={"vocab_size": 100_000, "num_slots": 16,
                        "emb_dim": 16, "step_size": 0.1, "sparse": True},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.widedeep:make_synthetic_sparse",
              "data_args": {"n": 32768, "vocab_size": 100_000,
                            "num_slots": 16}},
    )
    # total = epochs x dataset size, derived from the config itself so a
    # tuned data_args['n'] cannot silently skew the reported rate
    return {
        name: (cfg, cfg.params.num_epochs * cfg.user["data_args"]["n"])
        for name, cfg in (("fm", fm), ("widedeep", wd), ("fm-hash", fmh))
    }


def run_single(config: JobConfig, total_examples: int) -> dict:
    devices = jax.devices()  # bounded probe already ran in main()
    server = JobServer(num_executors=len(devices),
                       device_pool=DevicePool(devices))
    server.start()
    try:
        t0 = time.perf_counter()
        server.submit(config).result(timeout=3600)
        wall = time.perf_counter() - t0
    finally:
        server.shutdown(timeout=120)
    return {
        "metric": f"{config.job_id} throughput",
        "value": round(total_examples / wall, 1),
        "unit": "samples/sec",
        "examples": total_examples,
        "wall_sec": round(wall, 2),
    }


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    jobs, totals = bench.job_configs(1.0)
    table = {c.job_id.removeprefix("bench-"): (c, totals[c.job_id])
             for c in jobs}
    table.update(_sparse_jobs())
    if which != "all" and which not in table:
        sys.exit(f"unknown app {which!r}; available: {sorted(table)} or 'all'")
    names = list(table) if which == "all" else [which]
    from harmony_tpu.utils.devices import discover_devices

    try:
        discover_devices()
    except RuntimeError as e:
        for name in names:
            cfg, _ = table[name]
            print(json.dumps({
                "metric": f"{cfg.job_id} throughput",  # same key as success
                "value": None, "unit": "samples/sec",
                "error": f"accelerator unreachable: {e}",
            }))
        return
    for name in names:
        cfg, total = table[name]
        # per-job containment: one failing app (or a chip that wedges
        # mid-run, after the up-front probe passed) must not abort the
        # remaining apps or leave gaps in the metric series
        try:
            print(json.dumps(run_single(cfg, total)))
        except Exception as e:  # noqa: BLE001 - recorded as a metric line
            print(json.dumps({
                "metric": f"{cfg.job_id} throughput",
                "value": None, "unit": "samples/sec",
                "error": f"{type(e).__name__}: {e}",
            }))


if __name__ == "__main__":
    main()
