#!/usr/bin/env python
"""Online serving plane — latency/throughput A/B across the two serving
levers, idle and against live training (docs/SERVING.md).

One fixed closed-loop read storm (8 client threads, skewed keys, the
SAME pregenerated key streams for every arm — equal offered load by
construction) against a live DenseTable through the ServingEndpoint's
framed wire, across the lever grid:

  * ``unbatched``       — batch window 0, cache 0: every lookup is its
    own lock-held gather (the baseline the micro-batching claim is
    measured against);
  * ``batched``         — window 2 ms: concurrent lookups coalesce into
    ONE keyed gather (the leader waits out the window, so the win is
    queueing-delay removed minus window added);
  * ``cached``          — ByteLRU hot rows only (layout+data-version
    keyed), no coalescing;
  * ``batched_cached``  — both levers, the production default.

Then the two endpoint configs that bracket the grid rerun CONCURRENT
with a training loop (multi_update bursts on the same table) to measure
interference both ways: serving p99 under training, and training
updates/sec with and without the storm.

In-bench consistency gate (asserted before any number is reported):
during the concurrent-training arm, a dedicated reader does ``pinned``
lookups throughout and every response must be bit-identical to the
committed chain epoch's durable bytes and stamped with its epoch — a
torn or drifting pinned read fails the bench, it does not get averaged.

CPU-backend honesty note: gathers here cost ~ms on 1 host device, so
the batching win is lock-queueing removed; on a real TPU the gather is
µs but the dispatch+transfer fixed cost per lookup is proportionally
LARGER, which favors coalescing more, not less.

Writes benchmarks/SERVING_r20.json and prints ONE JSON line.
Run: python benchmarks/serving_bench.py
"""
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

ROUNDS = 2
CLIENTS = 8
READS_PER_CLIENT = 50
KEYS_PER_READ = 16
CAPACITY, WIDTH = 4096, 64
HOT_HEAD = 64  # skew: 3/4 of each read's keys land in this head

ARMS = (
    ("unbatched", 0.0, 0),
    ("batched", 2.0, 0),
    ("cached", 0.0, 64),
    ("batched_cached", 2.0, 64),
)
TRAIN_ARMS = ("unbatched", "batched_cached")
TRAIN_BATCH = 256


def _streams():
    """One fixed skewed key stream per (client, read) — identical for
    every arm, so offered load is equal by construction."""
    rng = np.random.default_rng(20)
    hot = rng.integers(0, HOT_HEAD,
                       size=(CLIENTS, READS_PER_CLIENT, 12))
    cold = rng.integers(0, CAPACITY,
                        size=(CLIENTS, READS_PER_CLIENT,
                              KEYS_PER_READ - 12))
    return np.concatenate([hot, cold], axis=-1).astype(np.int32)


def _make_table():
    from harmony_tpu.config.params import TableConfig
    from harmony_tpu.parallel import build_mesh
    from harmony_tpu.table import DenseTable, TableSpec

    mesh = build_mesh(jax.devices("cpu")[:1])
    table = DenseTable(
        TableSpec(TableConfig(table_id="srv-bench", capacity=CAPACITY,
                              value_shape=(WIDTH,), num_blocks=8)),
        mesh)
    table.multi_put(np.arange(CAPACITY, dtype=np.int32),
                    np.ones((CAPACITY, WIDTH), np.float32))
    return table


def _make_chain(root):
    """A committed 2-epoch chain for the pinned-consistency gate:
    epoch 1's durable bytes are exactly 2.0 everywhere."""
    from harmony_tpu.checkpoint import CheckpointManager
    from harmony_tpu.parallel import DevicePool
    from harmony_tpu.runtime import ETMaster

    master = ETMaster(DevicePool(jax.devices("cpu")[:1]))
    mgr = CheckpointManager.for_job(root, "srv-bench-pin")
    exs = master.add_executors(1)
    from harmony_tpu.config.params import TableConfig

    h = master.create_table(
        TableConfig(table_id="srv-bench-pin:m", capacity=32,
                    value_shape=(2,), num_blocks=8),
        [e.id for e in exs])
    for e in range(2):
        h.table.multi_update(list(range(32)), np.ones((32, 2), np.float32))
        mgr.checkpoint(h, commit=True, app_meta={"epoch": float(e)})
    return np.full((KEYS_PER_READ, 2), 2.0, np.float32)


def _storm(port, keys, lat_out):
    """The closed loop: CLIENTS threads, persistent sockets, each
    draining its fixed stream back-to-back. Returns wall seconds."""
    from harmony_tpu.serving import protocol

    errs = []

    def client(i):
        sock = protocol.connect(("127.0.0.1", port))
        try:
            mine = []
            for r in range(READS_PER_CLIENT):
                t0 = time.perf_counter()
                protocol.send_arrays(
                    sock, {"op": "lookup", "r": r, "job": "srv-bench",
                           "mode": "live"}, (keys[i, r],))
                frame = protocol.recv_frame(sock)
                dt = (time.perf_counter() - t0) * 1000.0
                if not frame or frame.get("op") != "rows":
                    raise RuntimeError(f"client {i} read {r}: {frame!r}")
                mine.append(dt)
            lat_out.extend(mine)
        except Exception as e:
            errs.append(e)
        finally:
            sock.close()

    t0 = time.perf_counter()
    ths = [threading.Thread(target=client, args=(i,))
           for i in range(CLIENTS)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=300)
    if errs:
        raise errs[0]
    return time.perf_counter() - t0


def _pct(ordered, p):
    return ordered[min(len(ordered) - 1, int(p * (len(ordered) - 1)))]


def run_arm(window_ms, cache_mb, keys, *, table=None, training=False,
            chkp_root=None, pinned_want=None):
    """One arm: (optionally) a training loop + pinned reader alongside
    the measured storm. Returns the arm's result dict."""
    from harmony_tpu.serving import ServingEndpoint, protocol

    table = table if table is not None else _make_table()
    ep = ServingEndpoint(table_fn=lambda job: table, cache_mb=cache_mb,
                         window_ms=window_ms, chkp_root=chkp_root)
    ep.start()
    stop = threading.Event()
    train_count = [0]
    pinned_reads = [0]
    gate_errs = []
    try:
        warm: "list[float]" = []
        _storm(ep.port, keys, warm)  # compile the coalesced gather shapes

        def trainer():
            rng = np.random.default_rng(1)
            while not stop.is_set():
                k = rng.integers(0, CAPACITY, TRAIN_BATCH).astype(np.int32)
                table.multi_update(
                    k, np.full((TRAIN_BATCH, WIDTH), 0.001, np.float32))
                train_count[0] += 1

        def pinned_reader():
            sock = protocol.connect(("127.0.0.1", ep.port))
            try:
                pk = np.arange(KEYS_PER_READ, dtype=np.int32)
                r = 0
                while not stop.is_set():
                    protocol.send_arrays(
                        sock, {"op": "lookup", "r": r,
                               "job": "srv-bench-pin", "mode": "pinned"},
                        (pk,))
                    frame = protocol.recv_frame(sock)
                    r += 1
                    if (not frame or frame.get("op") != "rows"
                            or frame.get("epoch") != 1
                            or not np.array_equal(
                                np.asarray(frame["data"][0], np.float32),
                                pinned_want)):
                        gate_errs.append(
                            f"pinned read {r}: "
                            f"{(frame or {}).get('epoch')!r}")
                        return
                    pinned_reads[0] += 1
            finally:
                sock.close()

        side = []
        if training:
            side = [threading.Thread(target=trainer),
                    threading.Thread(target=pinned_reader)]
            for t in side:
                t.start()
            time.sleep(0.1)  # the loops reach steady state

        lat: "list[float]" = []
        t_train0 = train_count[0]
        wall = _storm(ep.port, keys, lat)
        train_steps = train_count[0] - t_train0
        stop.set()
        for t in side:
            t.join(timeout=60)
        if gate_errs:
            raise AssertionError(
                f"pinned consistency gate failed: {gate_errs[0]}")
        st = ep.stats()
        cache = st.get("cache") or {}
        hits = cache.get("hits", 0)
        looked = hits + cache.get("misses", 0)
        ordered = sorted(lat)
        out = {
            "qps": round(len(lat) / wall, 1),
            "p50_ms": round(_pct(ordered, 0.50), 3),
            "p95_ms": round(_pct(ordered, 0.95), 3),
            "p99_ms": round(_pct(ordered, 0.99), 3),
            "batch_occupancy": st.get("batch_occupancy"),
            "cache_hit_rate": round(hits / looked, 3) if looked else None,
        }
        if training:
            out["train_updates_per_sec"] = round(train_steps / wall, 1)
            out["train_samples_per_sec"] = round(
                train_steps * TRAIN_BATCH / wall, 1)
            out["pinned_reads_ok"] = pinned_reads[0]
        return out
    finally:
        stop.set()
        ep.stop()


def _train_alone(table, seconds=1.0):
    """The interference denominator: the same update loop, no storm."""
    rng = np.random.default_rng(1)
    # warm the push program
    table.multi_update(
        rng.integers(0, CAPACITY, TRAIN_BATCH).astype(np.int32),
        np.full((TRAIN_BATCH, WIDTH), 0.001, np.float32))
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        k = rng.integers(0, CAPACITY, TRAIN_BATCH).astype(np.int32)
        table.multi_update(
            k, np.full((TRAIN_BATCH, WIDTH), 0.001, np.float32))
        n += 1
    return n * TRAIN_BATCH / (time.perf_counter() - t0)


def main() -> None:
    keys = _streams()
    arms: "dict[str, dict]" = {}
    # idle grid: best-of-ROUNDS per arm on p99 (host throughput drifts;
    # interleaved so no arm owns a quiet stretch)
    for _ in range(ROUNDS):
        for name, window_ms, cache_mb in ARMS:
            r = run_arm(window_ms, cache_mb, keys)
            if name not in arms or r["p99_ms"] < arms[name]["p99_ms"]:
                arms[name] = r
    # the bench's claim, asserted in-bench: both levers on must beat the
    # unbatched baseline on tail latency at equal offered load
    assert arms["batched_cached"]["p99_ms"] < arms["unbatched"]["p99_ms"], (
        f"micro-batching+cache lost on p99: "
        f"{arms['batched_cached']['p99_ms']} vs "
        f"{arms['unbatched']['p99_ms']}")

    with tempfile.TemporaryDirectory() as root:
        pinned_want = _make_chain(root)
        grid = {n: (w, c) for n, w, c in ARMS}
        train_arms = {}
        train_alone_sps = None
        for name in TRAIN_ARMS:
            w, c = grid[name]
            table = _make_table()
            if train_alone_sps is None:
                train_alone_sps = round(_train_alone(table), 1)
            train_arms[name] = run_arm(
                w, c, keys, table=table, training=True, chkp_root=root,
                pinned_want=pinned_want)
            assert train_arms[name]["pinned_reads_ok"] > 0, (
                "pinned gate never exercised")

    out = {
        "metric": "serving",
        "unit": "lookup ms (client-measured, closed loop)",
        "rounds": ROUNDS,
        "mode": (f"{CLIENTS} closed-loop clients x {READS_PER_CLIENT} "
                 f"lookups x {KEYS_PER_READ} keys, identical skewed "
                 "streams per arm (equal offered load), best-of per arm "
                 "on p99"),
        "workload": {"capacity": CAPACITY, "width": WIDTH,
                     "hot_head": HOT_HEAD,
                     "train_batch": TRAIN_BATCH},
        "arms": arms,
        "concurrent_training": {
            "train_alone_samples_per_sec": train_alone_sps,
            "arms": train_arms,
            "note": "same storm with a multi_update loop on the same "
                    "table; train_samples_per_sec vs the alone row is "
                    "the interference cost, and the pinned reader's "
                    "bit-exact gate ran throughout",
        },
        "consistency_gate": {
            "mode": "pinned",
            "checked_reads": sum(a["pinned_reads_ok"]
                                 for a in train_arms.values()),
            "result": "bit-identical to the committed epoch throughout",
        },
        "claim": {
            "p99_unbatched_ms": arms["unbatched"]["p99_ms"],
            "p99_batched_cached_ms": arms["batched_cached"]["p99_ms"],
            "p99_win": round(
                arms["unbatched"]["p99_ms"]
                / arms["batched_cached"]["p99_ms"], 2),
            "note": "asserted in-bench: batched+cached < unbatched on "
                    "p99 at equal offered load",
        },
        "note": "CPU backend: gathers are ~ms and serialize on the "
                "table lock, so coalescing removes queueing delay; on "
                "TPU the per-lookup dispatch overhead batching removes "
                "is proportionally larger",
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "SERVING_r20.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
