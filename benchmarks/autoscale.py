#!/usr/bin/env python
"""Telemetry-driven device autoscaling: policy-on vs policy-off A/B.

The FAIRNESS/POD_TENANTS successor for the closed loop (PR 15,
docs/SCHEDULING.md): a churning three-tenant mix on a 2-executor carved
pool, measured with the policy engine OFF (the pre-PR behavior: a
queued high-priority tenant waits for a carve to free) and ON in
``act`` mode (the engine detects the queued claimant, preempts a
device-idle low-priority tenant onto its sibling's executor — a shared
grant through a REAL elastic fence — and the freed carve unblocks the
claimant).

The mix:

* ``t-low-a`` / ``t-low-b`` — priority-0 elastic tenants, one executor
  each, DEVICE-IDLE by construction: a deterministic ``worker.epoch``
  delay rule (the blockmove.send delay-rule precedent) stalls each
  epoch boundary a fixed time, so the tenants hold their carves while
  barely using the device. The injected pacing is what makes the
  measurement honest on a saturated CPU host: a host-bound mix would
  hide any scheduling win inside CPU contention, while real pods idle
  devices exactly this way (the boundary stall deliberately sits
  OUTSIDE the TaskUnit admission scope — on this CPU backend COMP
  units meter serially across tenants, and a stall held inside a unit
  would serialize the claimant behind sleeping tenants, a backend
  artifact no real pod pays);
* ``t-high`` — a priority-1 compute tenant with a samples/sec SLO,
  submitted once the low tenants are mid-run. Under carve max_share=1
  both executors are taken, so it QUEUES — the contention the policy
  resolves.

Reported per arm: aggregate samples/sec (total examples / makespan),
the high tenant's end-to-end SLO attainment (examples / (completion -
submit) over its target — queue time counts, exactly as an operator
sees it), time-to-rebalance (t-high submit -> its dispatch start), and
cross-arm loss parity per tenant (fences must not change the math).
Interleaved rounds, best-of per arm. CPU-mesh numbers — comparable
across rounds, not to a chip.

Writes benchmarks/AUTOSCALE_<suffix>.json (argv[1], default r15);
prints ONE JSON line. Run: python benchmarks/autoscale.py
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

METRIC = ("autoscale A/B: aggregate samples/sec + SLO attainment, "
          "policy off vs act (churning 3-tenant mix, 2-executor carve)")
OUT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    f"AUTOSCALE_{sys.argv[1] if len(sys.argv) > 1 else 'r15'}.json")

#: low tenants: paced (delay per epoch boundary) so the device idles
#: under them while they hold their carves
LOW_EPOCHS = 40
LOW_N = 64
DELAY_SEC = 0.35
#: high tenant: real compute, sized so in the OFF arm it finishes LAST
#: (its queue wait extends the makespan the policy then reclaims)
HI_EPOCHS = 24
HI_N = 262144
BATCHES = 2
#: the high tenant's samples/sec SLO — end-to-end (queue time counts)
HI_SLO_SPS = 450000.0
#: t-high enters once the low tenants are this far in (seconds)
CHURN_DELAY = 1.0


def _low_cfg(job_id, seed):
    from harmony_tpu.config.params import JobConfig, TrainerParams

    return JobConfig(
        job_id=job_id, app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=LOW_EPOCHS, num_mini_batches=BATCHES,
            model_chkp_period=1, priority=0,
            app_params={"num_classes": 4, "num_features": 16,
                        "features_per_partition": 4, "step_size": 0.1},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": LOW_N, "num_features": 16,
                            "num_classes": 4, "seed": seed},
              "elastic_shrink": True},
    )


def _hi_cfg():
    from harmony_tpu.config.params import JobConfig, TrainerParams

    return JobConfig(
        job_id="t-high", app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=HI_EPOCHS, num_mini_batches=BATCHES,
            priority=1, target_samples_per_sec=HI_SLO_SPS,
            app_params={"num_classes": 16, "num_features": 256,
                        "features_per_partition": 32, "step_size": 0.05},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": HI_N, "num_features": 256,
                            "num_classes": 16, "seed": 5}},
    )


def _pace_low_tenants():
    """Deterministic per-epoch host stall on the low tenants only —
    carve-holding, device-idle tenants; t-high is untouched."""
    from harmony_tpu import faults

    faults.arm(faults.FaultPlan([
        faults.FaultRule("worker.epoch", match={"job": jid},
                         count=-1, action="delay", delay_sec=DELAY_SEC)
        for jid in ("t-low-a", "t-low-b")
    ]))


def _final_loss(result):
    (w,) = result["workers"].values()
    return round(w["losses"][-1], 6)


def _run_arm(policy_on, low_epochs=LOW_EPOCHS, hi_epochs=HI_EPOCHS):
    """One full mix under a fresh in-process pod server; returns the
    measured section dict."""
    from harmony_tpu import faults
    from harmony_tpu.jobserver import joblog
    from harmony_tpu.jobserver.pod import PodJobServer
    from harmony_tpu.jobserver.scheduler import CarveScheduler
    from harmony_tpu.metrics import accounting

    env = {
        "HARMONY_POLICY": "act" if policy_on else "off",
        "HARMONY_POLICY_PERIOD": "0.4",
        "HARMONY_POLICY_COOLDOWN": "2",
        "HARMONY_POLICY_CONFIRM": "2",
        "HARMONY_OBS_SCRAPE_PERIOD": "0.4",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    accounting.reset_ledger()
    joblog.clear_events()
    root = tempfile.mkdtemp(prefix="harmony-autoscale-")
    srv = PodJobServer(num_executors=2, num_followers=0,
                       scheduler=CarveScheduler(min_slice=1, max_share=1),
                       chkp_root=os.path.join(root, "chkp"))
    srv.start()
    srv.serve_pod(0)
    try:
        _pace_low_tenants()
        t0 = time.monotonic()
        futs = {"t-low-a": srv.submit(_low_cfg("t-low-a", seed=1)),
                "t-low-b": srv.submit(_low_cfg("t-low-b", seed=2))}
        time.sleep(CHURN_DELAY)
        hi_submit = time.monotonic()
        futs["t-high"] = srv.submit(_hi_cfg())
        done, results = {}, {}
        for jid, f in futs.items():
            results[jid] = f.result(timeout=900)
            done[jid] = time.monotonic()
        makespan = max(done.values()) - t0
        hi_elapsed = done["t-high"] - hi_submit
        hi_start = srv.job_walls.get("t-high", (None, None))[0]
        ttr = (hi_start - hi_submit) if hi_start is not None else None
        examples = {"t-low-a": low_epochs * LOW_N,
                    "t-low-b": low_epochs * LOW_N,
                    "t-high": hi_epochs * HI_N}
        hi_sps = examples["t-high"] / hi_elapsed
        actions = [dict(e, job=jid)
                   for jid, evs in joblog.job_events(limit=64).items()
                   for e in evs
                   if e.get("kind") == "policy" and e.get("executed")]
        return {
            "policy": "act" if policy_on else "off",
            "makespan_sec": round(makespan, 2),
            "agg_sps": round(sum(examples.values()) / makespan, 1),
            "hi_end_to_end_sps": round(hi_sps, 1),
            "slo_attainment": round(min(1.0, hi_sps / HI_SLO_SPS), 4),
            "time_to_rebalance_sec": (round(ttr, 2)
                                      if ttr is not None else None),
            "policy_actions": [
                {"job": a.get("job", "?"), "action": a["action"],
                 "outcome": a["outcome"]} for a in actions],
            "losses": {j: _final_loss(results[j]) for j in results},
            "elastic": {j: results[j].get("elastic", {}).get("attempts", 1)
                        for j in results},
        }
    finally:
        faults.disarm()
        try:
            srv.shutdown(timeout=120)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            shutil.rmtree(root, ignore_errors=True)


def run_autoscale(rounds: int = 2) -> dict:
    """Interleaved OFF/ON rounds, best-of (highest agg_sps) per arm;
    importable — bench.py's ``measure_autoscale`` hook runs a 1-round
    version so the headline series ride every BENCH line."""
    arms = {"off": [], "act": []}
    # warmup: compile every program shape once so neither timed arm
    # pays a compile the other inherits (interleaving absorbs drift,
    # not one-time costs)
    _run_arm(policy_on=False, low_epochs=LOW_EPOCHS, hi_epochs=HI_EPOCHS)
    for _ in range(rounds):
        arms["off"].append(_run_arm(policy_on=False))
        arms["act"].append(_run_arm(policy_on=True))
    best = {arm: max(rs, key=lambda r: r["agg_sps"])
            for arm, rs in arms.items()}
    off, act = best["off"], best["act"]
    parity = all(off["losses"][j] == act["losses"][j]
                 for j in ("t-low-b", "t-high"))
    # t-low-a is packed mid-run in the act arm (mesh moves executors);
    # its parity is asserted separately so a drift is named, not hidden
    parity_packed = off["losses"]["t-low-a"] == act["losses"]["t-low-a"]
    return {
        "metric": METRIC,
        "unit": "samples/sec aggregate (policy act arm)",
        "value": act["agg_sps"],
        "agg_sps": act["agg_sps"],
        "slo_attainment": act["slo_attainment"],
        "agg_speedup": round(act["agg_sps"] / off["agg_sps"], 3),
        "attainment_gain": round(
            act["slo_attainment"] - off["slo_attainment"], 4),
        "time_to_rebalance_sec": act["time_to_rebalance_sec"],
        "loss_parity": bool(parity and parity_packed),
        "off": off,
        "act": act,
        "rounds": rounds,
        "mix": {"low_epochs": LOW_EPOCHS, "low_n": LOW_N,
                "pace_delay_sec": DELAY_SEC, "hi_epochs": HI_EPOCHS,
                "hi_n": HI_N, "hi_slo_sps": HI_SLO_SPS,
                "batches": BATCHES},
        "host_cores": os.cpu_count(),
        "note": (
            "2-executor CPU carve (max_share=1), paced low tenants "
            "(deterministic worker.epoch boundary delay -> device "
            "idle) + a queued priority-1 SLO tenant. OFF: the claimant "
            "waits for a carve to free; ACT: the policy preempts the "
            "lowest-priority tenant onto its sibling's executor (a "
            "shared grant through a real elastic fence) and the freed "
            "executor unblocks the claimant. agg_sps = total examples "
            "/ makespan; slo_attainment is END-TO-END (queue time "
            "counts); time_to_rebalance = claimant submit -> dispatch "
            "start."),
    }


def main() -> None:
    try:
        out = run_autoscale(rounds=2)
    except Exception as e:  # noqa: BLE001 - still print one line
        print(json.dumps({"metric": METRIC, "value": None,
                          "error": f"{type(e).__name__}: {e}"}))
        return
    with open(OUT_PATH, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
