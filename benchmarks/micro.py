#!/usr/bin/env python
"""Microbenchmarks for the framework's data-plane primitives.

BASELINE.md's north-star metrics are (a) aggregate multi-tenant throughput
(bench.py at the repo root) and (b) ET push/pull bandwidth — this file
measures (b) plus the other primitives a capacity-planning reader needs:

  table      pull (all-gather of the sharded model) and push (delta fold)
             bandwidth through DenseTable.apply_step — the analogue of the
             reference's per-batch multiGetOrInit/multiUpdate path
             (SURVEY.md §3.2 PULL/PUSH TaskUnits).
  reshard    live migration cost: DenseTable.reshard between two mesh
             layouts, reported as bytes moved per second (the reference's
             MoveInitMsg/DataMsg block transfer, SURVEY.md §3.4).
  attention  flash vs naive attention wall time (the framework's Pallas
             kernel path where supported, jittable fallback elsewhere).
  multiget   host-path random-key multi_get/multi_update ops/sec (the
             sparse/irregular access path, e.g. embedding lookups).
  sparse     DeviceHashTable fused pull/push keys/sec — the hash-backed
             embedding hot path (admission + gather + fold in one step).
  mxu        dense bf16 matmul achieved FLOP/s and MFU (fraction of the
             chip's peak) — the ceiling every MXU-shaped op is judged
             against (BASELINE.md measurement plan; per-batch analogue of
             the reference's metrics.avsc:164-201 compute records).
  mxupush    the size-gated MXU duplicate-fold push route (one-hot matmul
             fold, table/table.py) vs the scatter route — GB/s both ways
             plus the fold's achieved FLOP/s.
  ringflash  the ring-attention flash inner compiled under shard_map —
             correctness + speed vs the einsum inner (gates flipping
             ring_attention's inner='auto' to flash-on-TPU).
  stall      job stall during a live migration: an MLR job trains while
             an executor drains; reports the blocking move, the next
             epoch's relayout overhead, and bytes moved.
  chkp       two-stage checkpoint save/commit/restore throughput on a
             64 MB table (.blk v2 codec when the native lib is built;
             commit copies into staging then renames, so it is O(size)).

Attention also reports achieved FLOP/s + MFU. MFU is null off-TPU (no
meaningful peak). Run on the real chip and commit the JSON.

  roofline   ANALYTIC expected-performance model (v5e roofline) for every
             headline kernel at its bench shape — FLOPs, HBM bytes, AI,
             binding resource, expected-MFU range with stated basis. No
             device needed: the model stands next to the unmeasured flag
             whenever the chip transport is wedged.

Run:  python benchmarks/micro.py [table|reshard|attention|multiget|sparse|mxu|mxupush|ringflash|stall|chkp|roofline|all]

Each section prints one JSON line so results diff cleanly across rounds.
Uses whatever backend JAX is pointed at (real chip under axon; set
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 for
the virtual multi-device mesh).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from harmony_tpu.utils.platform import mirror_env_platform_request

mirror_env_platform_request()  # JAX_PLATFORMS=cpu must mean cpu (axon hook)
import jax.numpy as jnp
import numpy as np

from harmony_tpu.config import TableConfig
from harmony_tpu.parallel import build_mesh
from harmony_tpu.table import DenseTable, TableSpec
from harmony_tpu.utils.devices import discover_devices

from common import mfu, timed_chain, timed_inner  # noqa: E402 (shared helpers)

REPEATS = 10


def _mesh():
    devs = jax.devices()
    data = 2 if len(devs) % 2 == 0 and len(devs) > 1 else 1
    return build_mesh(devs, data=data)


def _time_chain(step, state):
    dt, _ = timed_chain(step, state, repeats=REPEATS)
    return dt


def _time_inner(body, state, inner: int = 32):
    from harmony_tpu.utils.platform import tpu_backend

    # the inner fold amortizes the remote-attach per-program round trip;
    # off-TPU there is no tunnel and interpret-mode kernels make big inner
    # loops unaffordable — time single programs there
    if not tpu_backend():
        inner = 1
    dt, _ = timed_inner(body, state, inner=inner, outer=3)
    return dt


def bench_table() -> dict:
    """Pull+push bandwidth through one fused step over the job mesh."""
    mesh = _mesh()
    capacity, width = 16384, 256          # 16 MB model
    spec = TableSpec(TableConfig(
        table_id="bench", capacity=capacity, value_shape=(width,),
        num_blocks=64, update_fn="add",
    ))
    table = DenseTable(spec, mesh)
    model_bytes = capacity * width * 4

    def step(arr):
        model = spec.pull_all(arr)                 # PULL (all-gather)
        delta = model * 1e-6                       # touch every element
        return spec.push_all(arr, delta)           # PUSH (fold)

    dt = _time_inner(step, table.array)            # arr -> arr: chained
    gbps = 2 * model_bytes / dt / 1e9              # pulled + pushed
    return {"metric": "table pull+push bandwidth", "value": round(gbps, 2),
            "unit": "GB/s", "model_mb": model_bytes // 2**20,
            "devices": len(mesh.devices.flat)}


def bench_reshard() -> dict:
    """Live re-sharding cost between two mesh layouts."""
    devs = jax.devices()
    if len(devs) < 2:
        return {"metric": "reshard bandwidth", "value": None,
                "unit": "GB/s", "note": "needs >=2 devices"}
    capacity, width = 16384, 256
    spec = TableSpec(TableConfig(
        table_id="bench-rs", capacity=capacity, value_shape=(width,),
        num_blocks=64, update_fn="add",
    ))
    m1 = build_mesh(devs, data=1)
    m2 = build_mesh(devs, data=len(devs))
    table = DenseTable(spec, m1)
    model_bytes = capacity * width * 4
    t0 = time.perf_counter()
    n = 0
    for _ in range(REPEATS // 2):
        table.reshard(m2)
        table.reshard(m1)
        n += 2
    from harmony_tpu.utils.platform import hard_sync

    hard_sync(table.array)  # each reshard depends on the last: one chain
    dt = (time.perf_counter() - t0) / n
    return {"metric": "reshard bandwidth", "value": round(model_bytes / dt / 1e9, 2),
            "unit": "GB/s", "model_mb": model_bytes // 2**20,
            "devices": len(devs)}


def bench_attention() -> dict:
    """Framework attention kernel vs the naive O(S^2)-memory reference —
    bf16 operands at head_dim 128 (the MXU-native configuration the
    kernel is built for; the round-2 capture fed fp32 at d=64 and timed
    the casts, not the kernel), plus a (block_q, block_k) sweep so the
    reported number is the kernel's best config on THIS device."""
    from harmony_tpu.ops import flash_attention
    from harmony_tpu.utils.platform import tpu_backend

    b, h, s, d = 4, 8, 2048, 128
    dt = jnp.bfloat16 if tpu_backend() else jnp.float32
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (b, h, s, d), jnp.float32).astype(dt)
    k = jax.random.normal(k2, (b, h, s, d), jnp.float32).astype(dt)
    v = jax.random.normal(k3, (b, h, s, d), jnp.float32).astype(dt)

    def naive(q, k, v):
        a = jnp.einsum("bhsd,bhtd->bhst", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((s, s), bool))
        a = jnp.where(mask, a, -jnp.inf)
        p = jax.nn.softmax(a, -1).astype(v.dtype)
        return jnp.einsum("bhst,bhtd->bhsd", p, v)

    # chain the query through the op (output shape == q shape): every
    # iteration is in the compiled loop's graph and q never re-uploads
    t_naive = _time_inner(lambda qq: naive(qq, k, v), q, inner=16)
    # causal attention FLOPs: QK^T + AV = 2 x 2bhs^2d, halved by the mask
    flops = 2 * b * h * s * s * d
    sweep = {}
    best_cfg, t_flash = None, None
    # off-TPU the kernel runs interpreted (python-level grid) — sweeping
    # 4 configs of meaningless numbers quadruples the CPU pass for nothing
    configs = ((256, 256), (256, 512), (512, 512), (512, 1024)) \
        if tpu_backend() else ((256, 256),)
    for bq, bk in configs:
        if s % bq or s % bk:
            continue
        t = _time_inner(
            lambda qq, bq=bq, bk=bk: flash_attention(
                qq, k, v, causal=True, block_q=bq, block_k=bk),
            q, inner=16)
        sweep[f"{bq}x{bk}"] = {"ms": round(t * 1e3, 2),
                               "mfu": _mfu(flops / t)}
        if t_flash is None or t < t_flash:
            t_flash, best_cfg = t, (bq, bk)
    out = {"metric": "flash attention speedup vs naive", "seq": s,
           "head_dim": d, "dtype": str(dt.__name__),
           "value": round(t_naive / t_flash, 2), "unit": "x",
           "naive_ms": round(t_naive * 1e3, 1),
           "flash_ms": round(t_flash * 1e3, 1),
           "flash_tflops": round(flops / t_flash / 1e12, 2),
           "best_blocks": f"{best_cfg[0]}x{best_cfg[1]}",
           "block_sweep": sweep}
    out["flash_mfu"] = _mfu(flops / t_flash)
    return out


_mfu = mfu


def bench_ringflash() -> dict:
    """The ring-attention flash inner, COMPILED under shard_map.

    ring.py's inner='auto' stays on the einsum fold until this section has
    run green on a real chip (interpret mode is validated in tests; the
    compiled Mosaic-under-shard_map path is the open question). Runs on
    however many devices are visible — on the single chip it exercises the
    1-device ring (the kernel-under-shard_map mechanics without ppermute);
    on a virtual mesh it exercises the full rotation. Reports correctness
    vs the einsum inner plus both times."""
    from harmony_tpu.ops.ring import ring_self_attention

    devs = jax.devices()
    n = len(devs)
    mesh = build_mesh(devs, data=1, seq=n, model=1)
    b, h, d = 2, 4, 64
    s_loc = 512
    s = s_loc * n
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(k1, (b, h, s, d), jnp.float32)
    k = jax.random.normal(k2, (b, h, s, d), jnp.float32)
    v = jax.random.normal(k3, (b, h, s, d), jnp.float32)

    from harmony_tpu.utils.platform import tpu_backend
    vma_kw = {} if tpu_backend() else {"check_vma": False}
    flash_fn = jax.jit(lambda q, k, v: ring_self_attention(
        q, k, v, mesh, seq_axis="seq", causal=True, inner="flash", **vma_kw))
    einsum_fn = jax.jit(lambda q, k, v: ring_self_attention(
        q, k, v, mesh, seq_axis="seq", causal=True, inner="einsum"))
    try:
        err = float(jnp.abs(flash_fn(q, k, v).astype(jnp.float32)
                            - einsum_fn(q, k, v).astype(jnp.float32)).max())
        if tpu_backend():
            # fold 8 rings into one program: amortizes the remote-attach
            # per-program round trip (separate compile from the err check)
            t_f = _time_inner(lambda qq: ring_self_attention(
                qq, k, v, mesh, seq_axis="seq", causal=True, inner="flash",
                **vma_kw), q, inner=8)
            t_e = _time_inner(lambda qq: ring_self_attention(
                qq, k, v, mesh, seq_axis="seq", causal=True, inner="einsum"),
                q, inner=8)
        else:
            # no tunnel off-TPU — reuse the fns the err check already
            # compiled (the interpret-mode flash compile is expensive)
            t_f, _ = timed_chain(lambda qq: flash_fn(qq, k, v), q, repeats=3)
            t_e, _ = timed_chain(lambda qq: einsum_fn(qq, k, v), q, repeats=3)
    except Exception as e:  # a red section must still be a JSON line
        return {"metric": "ring flash inner (compiled shard_map)",
                "value": None, "unit": "x vs einsum inner",
                "devices": n, "seq": s,
                "error": f"{type(e).__name__}: {e}"[:400]}
    return {"metric": "ring flash inner (compiled shard_map)",
            "value": round(t_e / t_f, 2), "unit": "x vs einsum inner",
            "devices": n, "seq": s, "max_abs_err": err,
            "flash_ms": round(t_f * 1e3, 1), "einsum_ms": round(t_e * 1e3, 1),
            "ok": err < 5e-3}


def bench_mxu() -> dict:
    """Dense bf16 matmul MFU — the roofline every MXU op is judged by."""
    n = 4096
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    a = jax.random.normal(k1, (n, n), jnp.bfloat16)
    b = jax.random.normal(k2, (n, n), jnp.bfloat16)
    # chain a through the product, rescaled so bf16 never overflows; the
    # elementwise scale fuses into the matmul epilogue (FLOPs still 2n^3)
    scale = jnp.bfloat16(1.0 / np.sqrt(n))
    dt = _time_inner(lambda aa: (aa @ b) * scale, a, inner=64)
    flops = 2 * n * n * n
    return {"metric": "mxu_dot bf16 achieved", "value": round(flops / dt / 1e12, 2),
            "unit": "TFLOP/s", "n": n, "mfu": _mfu(flops / dt)}


def bench_attnbwd() -> dict:
    """Flash attention BACKWARD — the Pallas dQ/dK/dV kernels
    (ops/attention.py custom_vjp) vs autodiff through the naive O(S^2)
    reference, same shape/dtype policy as the forward section. Times a
    full grad step (fwd + bwd) for both; the bwd-only cost is the grad
    time minus the matching forward time. Roofline expectation:
    benchmarks/micro.py roofline 'flash_bwd' (20-40% MFU)."""
    from harmony_tpu.ops import flash_attention
    from harmony_tpu.utils.platform import tpu_backend

    b, h, s, d = 4, 8, 2048, 128
    if not tpu_backend():
        # interpreted Pallas backward at s=2048 costs minutes of python
        # grid loops; keep the section runnable everywhere (numbers off
        # TPU are mechanics-smoke only — the bundle excludes them)
        b, h, s = 1, 2, 512
    dt = jnp.bfloat16 if tpu_backend() else jnp.float32
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(k1, (b, h, s, d), jnp.float32).astype(dt)
    k = jax.random.normal(k2, (b, h, s, d), jnp.float32).astype(dt)
    v = jax.random.normal(k3, (b, h, s, d), jnp.float32).astype(dt)

    def naive(q, k, v):
        a = jnp.einsum("bhsd,bhtd->bhst", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((s, s), bool))
        a = jnp.where(mask, a, -jnp.inf)
        p = jax.nn.softmax(a, -1).astype(v.dtype)
        return jnp.einsum("bhst,bhtd->bhsd", p, v)

    def loss_of(fn):
        # mean keeps the cotangent O(1) so bf16 grads stay in range
        return lambda qq, kk, vv: jnp.mean(
            fn(qq, kk, vv).astype(jnp.float32))

    grad_naive = jax.grad(loss_of(naive), argnums=(0, 1, 2))
    grad_flash = jax.grad(
        loss_of(lambda qq, kk, vv: flash_attention(qq, kk, vv, causal=True)),
        argnums=(0, 1, 2))

    def chain(gfn):
        # chain q through its own grad so iterations stay in-graph
        return lambda qq: gfn(qq, k, v)[0].astype(dt)

    t_naive = _time_inner(chain(grad_naive), q, inner=8)
    t_flash = _time_inner(chain(grad_flash), q, inner=8)
    # grad step = fwd + bwd; standard accounting: bwd = 2.5x fwd FLOPs
    fwd_flops = 2 * b * h * s * s * d
    step_flops = int(3.5 * fwd_flops)
    return {"metric": "flash attention BACKWARD (grad step) vs naive",
            "seq": s, "head_dim": d, "dtype": str(dt.__name__),
            "value": round(t_naive / t_flash, 2), "unit": "x",
            "naive_grad_ms": round(t_naive * 1e3, 1),
            "flash_grad_ms": round(t_flash * 1e3, 1),
            "flash_grad_tflops": round(step_flops / t_flash / 1e12, 2),
            "flash_grad_mfu": _mfu(step_flops / t_flash)}


def bench_roofline() -> dict:
    """ANALYTIC roofline for every headline kernel at its bench shape —
    no device needed, so the expected numbers exist even while the chip
    transport is wedged (round-4 verdict item 1: reviewers need the
    MODEL next to the unmeasured flag, not just a promise).

    Machine model (v5e, public spec): 197 bf16 TFLOP/s peak, 819 GB/s
    HBM — ridge at ~240 FLOP/byte. For each kernel: FLOPs, minimum HBM
    traffic, arithmetic intensity, the binding resource, the roofline
    wall time, and an expected-MFU RANGE whose basis is stated (pure
    roofline for clean matmuls; a derated range for kernels whose inner
    loop interleaves VPU work between MXU ops). When a chip capture
    exists, the measured section stands next to this model; until then
    THIS is the claim the kernels are built to."""
    PEAK = 197e12          # v5e dense bf16 FLOP/s (utils/platform._PEAK_BF16)
    BW = 819e9             # v5e HBM GB/s (public spec sheet)
    ridge = PEAK / BW

    def entry(flops, bytes_, eff_lo, eff_hi, basis):
        ai = flops / bytes_
        bound = "compute" if ai >= ridge else "memory"
        # roofline time at 100% efficiency of the binding resource
        t_roof = max(flops / PEAK, bytes_ / BW)
        # expected wall = roofline / efficiency; expected MFU uses the
        # FLOP clock even for memory-bound kernels (how MFU is reported)
        t_lo, t_hi = t_roof / eff_hi, t_roof / eff_lo
        return {
            "flops": round(flops / 1e9, 2), "gflops_unit": "GFLOP",
            "hbm_mb": round(bytes_ / 1e6, 1),
            "ai_flop_per_byte": round(ai, 1),
            "bound": bound,
            "roofline_ms": round(t_roof * 1e3, 3),
            "expected_ms": [round(t_lo * 1e3, 3), round(t_hi * 1e3, 3)],
            "expected_mfu": [round(flops / t_hi / PEAK, 3),
                             round(flops / t_lo / PEAK, 3)],
            "basis": basis,
        }

    kernels = {}
    # -- mxu: 4096^3 bf16 matmul (bench_mxu's shape) ---------------------
    n = 4096
    kernels["mxu_dot_4096"] = entry(
        2 * n**3, 3 * n * n * 2, 0.80, 0.95,
        "aligned 4096-cube bf16 matmul: MXU-tiled perfectly; large "
        "published XLA matmuls land 80-95% of peak")
    # -- flash attention fwd (bench_attention's shape) -------------------
    b, h, s, d = 4, 8, 2048, 128
    att_flops = 2 * b * h * s * s * d  # QK^T + AV, halved by causal mask
    att_bytes = 4 * b * h * s * d * 2  # q,k,v,o once each, bf16
    kernels["flash_fwd_b4h8_s2048_d128"] = entry(
        att_flops, att_bytes, 0.25, 0.50,
        "two MXU matmuls per tile with a VPU softmax (max/exp/rescale) "
        "between them; d=128 keeps the MXU fed. Public TPU flash "
        "kernels at this shape land 25-50% of peak; >=25% fwd MFU is "
        "the round-5 acceptance bar (3x+ over the measured r02 naive)")
    # -- flash attention bwd (ops/attention.py backward kernels) ---------
    kernels["flash_bwd_b4h8_s2048_d128"] = entry(
        int(2.5 * att_flops), int(1.75 * att_bytes), 0.20, 0.40,
        "dQ/dK/dV recompute-style backward = 2.5x fwd FLOPs (5 matmuls "
        "per tile vs 2), heavier VPU mixing -> derate below fwd")
    # -- 190M LM train step (benchmarks/lm.py train100m config) ----------
    params, seq, bsz = 190e6, 2048, 8
    lm_flops = 6 * params * seq * bsz  # fwd+bwd ~ 6*N per token
    lm_bytes = (2 * params * 2        # params read + grads written, bf16
                + 3 * bsz * seq * 512 * 2 * 24)  # rough activation traffic
    kernels["lm_190m_train_step"] = entry(
        lm_flops, int(lm_bytes), 0.25, 0.45,
        "transformer train step ~6N FLOPs/token; with remat + bf16 and "
        "d_model-scale matmuls the published XLA range on v5e is "
        "25-45% MFU; >=25% is the round-5 acceptance bar (r02 measured "
        "10.3% at 29.9M params - sub-MXU-size matmuls)")
    # -- table push: scatter vs MXU fold at bench_table's shape ----------
    cap, dim = 1 << 16, 256
    tbl_bytes = cap * dim * 4 * 3  # read + write table, read delta, fp32
    kernels["table_push_64k_x256"] = entry(
        2 * cap * dim, tbl_bytes, 0.50, 0.85,
        "pure streaming fold (1 MAC per element): memory-bound at "
        "AI<1; expected = 50-85% of HBM bandwidth")
    rows = {k: v for k, v in kernels.items()}
    return {"metric": "analytic roofline (v5e model)",
            "value": rows["flash_fwd_b4h8_s2048_d128"]["expected_mfu"][0],
            "unit": "min expected flash fwd MFU",
            "peak_bf16_tflops": PEAK / 1e12, "hbm_gbps": BW / 1e9,
            "ridge_flop_per_byte": round(ridge, 1),
            "kernels": rows,
            "note": ("analytic — carries the EXPECTED number for every "
                     "kernel the wedged chip has kept unmeasured; "
                     "measured sections replace this as captures land")}


def bench_mxupush() -> dict:
    """The keyed-push routes: XLA scatter vs the MXU duplicate-fold
    (one-hot matmul, table/table.py push via='mxu') ACROSS shapes, plus
    the AUTOTUNED choice (table/autotune.choose_push_route) — the round-3
    acceptance is chosen == best-of-both per shape (the old static
    capacity//256 gate picked the measured-slower route on chip)."""
    from harmony_tpu.table import autotune

    mesh = _mesh()
    # (capacity, width, nkeys): duplicate-heavy, sparse-into-huge, medium
    shapes = [(4096, 256, 8192), (65536, 64, 4096), (16384, 128, 16384)]
    rng = np.random.default_rng(0)
    out = {"metric": "mxu push route (measured choice vs best-of-both)",
           "unit": "GB/s", "devices": len(mesh.devices.flat), "shapes": []}
    mischosen = 0
    for capacity, width, nkeys in shapes:
        spec = TableSpec(TableConfig(
            table_id=f"bench-mp-{capacity}-{width}", capacity=capacity,
            value_shape=(width,), num_blocks=64, update_fn="add",
        ))
        table = DenseTable(spec, mesh)
        keys = jnp.asarray(rng.integers(0, capacity, nkeys), jnp.int32)
        deltas = jnp.asarray(
            rng.standard_normal((nkeys, width)), np.float32)
        push_bytes = nkeys * width * 4
        # deltas gain a zero-weight dependency on the loop-carried array
        # so the fold/scatter operand is NOT loop-invariant inside
        # timed_inner's fori_loop — XLA would hoist the one-hot fold out
        # of the loop and the section would time a dense add
        t_scatter = _time_inner(
            lambda a: spec.push(a, keys, deltas + 0.0 * a[0, 0],
                                via="scatter"),
            table.array)
        t_mxu = _time_inner(
            lambda a: spec.push(a, keys, deltas + 0.0 * a[0, 0], via="mxu"),
            table.array)
        chosen = autotune.choose_push_route(spec, mesh, nkeys, table=table)
        best = "mxu" if t_mxu < t_scatter else "scatter"
        # a mischoice only counts when the routes differ beyond noise
        # (autotune and this bench time with different harnesses; at a
        # near-tie shape either answer is right)
        if chosen != best and abs(t_mxu - t_scatter) > 0.1 * max(t_mxu,
                                                                 t_scatter):
            mischosen += 1
        row = {
            "capacity": capacity, "width": width, "keys": nkeys,
            "scatter_gbps": round(push_bytes / t_scatter / 1e9, 2),
            "mxu_gbps": round(push_bytes / t_mxu / 1e9, 2),
            "chosen": chosen, "best": best,
            "chosen_gbps": round(
                push_bytes / (t_mxu if chosen == "mxu" else t_scatter)
                / 1e9, 2),
        }
        # the fold is a [capacity, nkeys] x [nkeys, width] one-hot matmul
        fold_flops = 2 * capacity * nkeys * width
        row["fold_mfu"] = _mfu(fold_flops / t_mxu)
        out["shapes"].append(row)
        table.drop()
    # headline: the chosen-route bandwidth at the duplicate-heavy shape
    out["value"] = out["shapes"][0]["chosen_gbps"]
    out["mischosen_shapes"] = mischosen
    out["old_static_gate_note"] = (
        "static capacity//256 routed shape 0 to mxu; the measurement now "
        "decides per shape"
    )
    return out


def bench_multiget() -> dict:
    """Host-path random-key access (sparse/irregular pulls)."""
    mesh = _mesh()
    capacity, width, nkeys = 65536, 64, 4096
    spec = TableSpec(TableConfig(
        table_id="bench-mg", capacity=capacity, value_shape=(width,),
        num_blocks=64, update_fn="add",
    ))
    table = DenseTable(spec, mesh)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, capacity, nkeys)
    deltas = rng.standard_normal((nkeys, width), dtype=np.float32)
    t0 = time.perf_counter()
    for _ in range(REPEATS):
        table.multi_get(keys)
        table.multi_update(keys, deltas)
    dt = (time.perf_counter() - t0) / REPEATS
    return {"metric": "host multi_get+multi_update", "value": round(2 * nkeys / dt),
            "unit": "keys/sec", "keys_per_call": nkeys}


def bench_sparse() -> dict:
    """Fused sparse pull/push on the DeviceHashTable — the embedding-table
    hot path (admission + gather + scatter-fold in ONE jitted step, keys
    from the full int32 domain)."""
    from harmony_tpu.table import DeviceHashTable, HashTableSpec

    mesh = _mesh()
    slots, width, nkeys = 262144, 64, 8192
    spec = HashTableSpec(TableConfig(
        table_id="bench-sp", capacity=slots, value_shape=(width,),
        num_blocks=64, is_ordered=False, update_fn="add", sparse=True,
    ))
    table = DeviceHashTable(spec, mesh)
    rng = np.random.default_rng(0)
    universe = rng.choice(2**31 - 3, size=4 * nkeys, replace=False) + 1
    keys = jnp.asarray(universe[rng.integers(0, 4 * nkeys, nkeys)], jnp.int32)
    deltas = jnp.asarray(
        rng.standard_normal((nkeys, width)), jnp.float32
    )

    def run(state):
        state, vals, token = spec.pull(state, keys)
        return spec.push(state, token, deltas + 0.0 * vals)

    dt = _time_inner(run, table.state, inner=16)
    row_bytes = width * 4
    return {"metric": "sparse table fused pull+push", "value": round(2 * nkeys / dt),
            "unit": "keys/sec", "keys_per_step": nkeys,
            "mb_per_step": round(2 * nkeys * row_bytes / 2**20, 1),
            "devices": len(mesh.devices.flat)}


def bench_stall() -> dict:
    """Job stall during a live migration (BASELINE.md measurement plan:
    're-sharding cost: blocks moved x bytes, job stall time during
    migration'). An MLR job trains over 2 executors; after a mid epoch,
    executor 0 DRAINS — all its blocks move to executor 1, shrinking the
    owning set so the table physically re-materializes on the new layout
    (a move that keeps the owning set is just an ownership-map edit; see
    TableHandle.move_blocks). Reported: the blocking move itself, the
    migrated-vs-clean epoch overhead (the next dispatch rebuilds for the
    new layout), and bytes moved."""
    from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic
    from harmony_tpu.config.params import TrainerParams
    from harmony_tpu.dolphin import (
        TrainerContext, TrainingDataProvider, WorkerTasklet,
    )
    from harmony_tpu.metrics.collector import EpochMetrics, MetricCollector
    from harmony_tpu.parallel.mesh import DevicePool
    from harmony_tpu.runtime.master import ETMaster

    devs = jax.devices()
    if len(devs) < 2:
        return {"metric": "live migration stall", "value": None,
                "unit": "sec", "note": "needs >=2 devices"}
    master = ETMaster(DevicePool(devs[:2]))
    exs = master.add_executors(2)
    # the headline MLR shape (8 MB model) so the move transfers real bytes
    trainer = MLRTrainer(num_classes=256, num_features=8192,
                         features_per_partition=512)
    handle = master.create_table(trainer.model_table_config(),
                                 [e.id for e in exs])
    epochs, nb, mig_epoch = 9, 4, 4
    x, y = make_synthetic(512, num_features=8192, num_classes=256)
    spec = handle.table.spec
    row_bytes = int(np.prod(spec.value_shape)) * spec.dtype.itemsize
    moved = {}

    import threading

    def do_move():
        # drain ALL of ex0's blocks: the owning set shrinks, forcing the
        # physical re-materialization a partial move would skip. Runs on
        # its own thread — the production shape (the orchestrator moves
        # while workers train) — so the announce->prewarm->flip pipeline
        # overlaps training instead of being charged to the job.
        from harmony_tpu.utils.platform import hard_sync

        try:
            n_move = handle.block_manager.block_counts()[exs[0].id]
            t0 = time.perf_counter()
            handle.move_blocks(exs[0].id, exs[1].id, n_move)
            # sync INSIDE the timed region: device_put returns before bytes
            # move on async/lazy backends, and the transfer would otherwise
            # masquerade as the next epoch's relayout overhead
            hard_sync(handle.table.array)
            moved["sec"] = time.perf_counter() - t0
            moved["blocks"] = n_move
            moved["bytes"] = n_move * spec.block_size * row_bytes
            moved["owners_after"] = len(handle.owning_executors())
        except BaseException as e:  # noqa: BLE001 - surfaced below
            moved["error"] = f"{type(e).__name__}: {e}"

    mover = threading.Thread(target=do_move, name="stall-mover")

    def on_epoch(epoch):
        if epoch == mig_epoch:
            mover.start()

    walls: dict = {}
    collector = MetricCollector(
        sink=lambda m: walls.__setitem__(m.epoch_idx, m.epoch_time_sec)
        if isinstance(m, EpochMetrics) else None)
    worker = WorkerTasklet(
        "stall-bench",
        TrainerContext(params=TrainerParams(num_epochs=epochs,
                                            num_mini_batches=nb,
                                            comm_probe_period=0),
                       model_table=handle.table),
        trainer,
        TrainingDataProvider([x, y], nb),
        handle.table.mesh,
        collector=collector,
        epoch_callback=on_epoch,
    )
    worker.run()
    mover.join(timeout=120)
    if mover.is_alive():
        return {"metric": "live migration stall (job-observed excess wall)",
                "value": None, "unit": "sec", "error": "mover thread hung"}
    if "error" in moved:
        return {"metric": "live migration stall (job-observed excess wall)",
                "value": None, "unit": "sec",
                "error": f"move failed: {moved['error']}"}
    # JOB-OBSERVED stall: the excess wall time of the epochs overlapping
    # the migration (announce+prewarm+flip run on the mover thread; the
    # job pays only lock waits, the prewarm's device time, and whatever
    # relayout remains at the next rebuild). Clean epochs exclude epoch 0
    # (first-compile) and the migration-overlapped window.
    # every epoch from the trigger onward may overlap the mover thread;
    # clean epochs are strictly BEFORE it (minus the first-compile epoch)
    mig_window = tuple(range(mig_epoch, epochs))
    clean = [w for e, w in walls.items() if e not in (0, *mig_window)]
    clean_med = sorted(clean)[len(clean) // 2]
    stall = sum(max(walls[e] - clean_med, 0.0)
                for e in mig_window if e in walls)
    assert moved["owners_after"] == 1, "drain must shrink the owning set"
    return {
        "metric": "live migration stall (job-observed excess wall)",
        "value": round(stall, 3),
        "unit": "sec",
        "mover_wall_sec": round(moved["sec"], 3),
        "stall_vs_clean_epochs": round(stall / clean_med, 2),
        "blocks_moved": moved["blocks"],
        "bytes_moved": moved["bytes"],
        "clean_epoch_sec": round(clean_med, 3),
        "devices": 2,
    }


def bench_chkp() -> dict:
    """Two-stage checkpoint save/commit/restore throughput on a 64 MB
    table (the reference's ChkpManagerSlave temp->HDFS path; here the
    native .blk v2 codec + posix rename commit — SURVEY §3.5)."""
    import shutil
    import tempfile

    from harmony_tpu.checkpoint.manager import CheckpointManager
    from harmony_tpu.parallel.mesh import DevicePool
    from harmony_tpu.runtime.master import ETMaster

    devs = jax.devices()
    master = ETMaster(DevicePool(devs[: min(2, len(devs))]))
    exs = master.add_executors(min(2, len(devs)))
    capacity, width = 65536, 256                     # 64 MB fp32
    handle = master.create_table(
        TableConfig(table_id="bench-ck", capacity=capacity,
                    value_shape=(width,), num_blocks=64, update_fn="add"),
        [e.id for e in exs],
    )
    model_mb = capacity * width * 4 / 2**20
    from harmony_tpu import native
    from harmony_tpu.utils.platform import hard_sync

    # the table's device-side init must not bill to the stage timer
    hard_sync(handle.table.array)
    root = tempfile.mkdtemp(prefix="harmony-chkp-bench-")
    try:
        mgr = CheckpointManager(os.path.join(root, "temp"),
                                os.path.join(root, "commit"))
        t0 = time.perf_counter()
        cid = mgr.checkpoint(handle)                 # stage (device->disk)
        t_stage = time.perf_counter() - t0
        t0 = time.perf_counter()
        # durable commit: copies blocks into staging then renames — O(size)
        mgr.commit(cid)
        t_commit = time.perf_counter() - t0
        t0 = time.perf_counter()
        restored = mgr.restore(master, cid, [e.id for e in exs],
                               table_id="bench-ck-r")
        np.asarray(restored.table.pull_array())      # force materialization
        t_restore = time.perf_counter() - t0
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "metric": "checkpoint save/restore",
        "value": round(model_mb / t_stage, 1),
        "unit": "MB/s stage",
        "model_mb": round(model_mb),
        "codec": "blk" if native.available() else "npy",
        "stage_s": round(t_stage, 2),
        "commit_s": round(t_commit, 3),
        "restore_mbps": round(model_mb / t_restore, 1),
        "restore_s": round(t_restore, 2),
    }


SECTIONS = {
    "table": bench_table,
    "reshard": bench_reshard,
    "attention": bench_attention,
    "multiget": bench_multiget,
    "sparse": bench_sparse,
    "mxu": bench_mxu,
    "mxupush": bench_mxupush,
    "ringflash": bench_ringflash,
    "stall": bench_stall,
    "chkp": bench_chkp,
    "roofline": bench_roofline,
    "attnbwd": bench_attnbwd,
}
# reported metric name + unit per section, so ERROR lines land in the same
# metric series a success would (same keys a tracker would index on)
SECTION_METRICS = {
    "ringflash": ("ring flash inner (compiled shard_map)", "x vs einsum inner"),
    "table": ("table pull+push bandwidth", "GB/s"),
    "reshard": ("reshard bandwidth", "GB/s"),
    "attention": ("flash attention speedup vs naive", "x"),
    "multiget": ("host multi_get+multi_update", "keys/sec"),
    "sparse": ("sparse table fused pull+push", "keys/sec"),
    "mxu": ("mxu_dot bf16 achieved", "TFLOP/s"),
    "mxupush": ("mxu push route", "GB/s"),
    "stall": ("live migration stall", "sec"),
    "chkp": ("checkpoint save/restore", "MB/s stage"),
    "roofline": ("analytic roofline (v5e model)", "min expected flash fwd MFU"),
    "attnbwd": ("flash attention BACKWARD (grad step) vs naive", "x"),
}


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which != "all" and which not in SECTIONS:
        sys.exit(f"unknown section {which!r}; have {sorted(SECTIONS)} or 'all'")
    names = list(SECTIONS) if which == "all" else [which]
    # ONE bounded probe up front: every section's first jax op would
    # otherwise block forever on a wedged transport.
    try:
        discover_devices()
    except RuntimeError as e:
        for name in names:
            metric, unit = SECTION_METRICS[name]
            print(json.dumps({"metric": metric, "value": None, "unit": unit,
                              "error": f"accelerator unreachable: {e}"}))
        return
    for name in names:
        print(json.dumps(SECTIONS[name]()))


if __name__ == "__main__":
    main()
