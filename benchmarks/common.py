"""Shared helpers for the benchmark scripts (micro.py, lm.py)."""
from __future__ import annotations

import time

import jax


def timed(fn, *args, repeats: int = 10) -> float:
    """Mean wall time per call after a warmup/compile dispatch (which also
    drains the device queue)."""
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / repeats


def mfu(achieved_flops: float):
    """achieved/peak for ONE chip, or None off-TPU."""
    from harmony_tpu.utils.platform import device_is_tpu, peak_bf16_flops

    d = jax.devices()[0]
    peak = peak_bf16_flops(d) if device_is_tpu(d) else None
    return round(achieved_flops / peak, 3) if peak else None
