"""Shared helpers for the benchmark scripts (micro.py, lm.py)."""
from __future__ import annotations

import time

import jax


def timed(fn, *args, repeats: int = 10) -> float:
    """Mean wall time per call after a warmup/compile dispatch (which also
    drains the device queue)."""
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / repeats


def mfu(achieved_flops: float):
    """achieved/peak for ONE chip, or None off-TPU."""
    from harmony_tpu.utils.platform import device_is_tpu, peak_bf16_flops

    d = jax.devices()[0]
    peak = peak_bf16_flops(d) if device_is_tpu(d) else None
    return round(achieved_flops / peak, 3) if peak else None


# ---------------------------------------------------------------------------
# Pod-launch harness shared by benchmarks/pod.py and tests/test_multihost.py
# ---------------------------------------------------------------------------

def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def sanitized_cpu_env(devices_per_proc: int) -> dict:
    """Child env for spawned pod/distributed workers: strip every TPU-claim
    var (PALLAS_AXON_POOL_IPS and AXON_* all trigger the experimental TPU
    client, which hangs backend init on a wedged transport) and force an
    n-virtual-device CPU backend."""
    import os

    env = dict(os.environ)
    for var in list(env):
        if var == "PALLAS_AXON_POOL_IPS" or var.startswith("AXON_"):
            env.pop(var)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    return env


def wait_for_ready(proc, deadline_s: float, marker: str = "READY") -> bool:
    """Read ``proc.stdout`` lines until ``marker`` (skipping benign startup
    prints), EOF, or the deadline. Each readline runs on a helper thread so
    a silently-wedged process hits the deadline instead of blocking
    forever."""
    import threading
    import time

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        box = {}
        t = threading.Thread(
            target=lambda: box.update(line=proc.stdout.readline()),
            daemon=True,
        )
        t.start()
        t.join(max(0.1, deadline - time.monotonic()))
        line = box.get("line", "")
        if line.strip() == marker:
            return True
        if not line:  # EOF: process exited without the marker
            return False
    return False
