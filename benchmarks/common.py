"""Shared helpers for the benchmark scripts (micro.py, lm.py)."""
from __future__ import annotations

import time

import jax


def timed_chain(step, state, repeats: int = 10):
    """Mean wall time per iteration of ``state = step(state)``.

    The data dependency between iterations makes every one of them part of
    the final state's graph, so the closing hard_sync provably covers the
    whole loop even on a lazy-dispatch backend that evaluates only the
    demanded subgraph (and it avoids per-call re-upload of unchanged
    operands, which such clients charge to independent calls). Returns
    (seconds_per_iter, final_state)."""
    from harmony_tpu.utils.platform import hard_sync

    state = step(state)  # warmup: compile + first execution
    hard_sync(state)
    t0 = time.perf_counter()
    for _ in range(repeats):
        state = step(state)
    hard_sync(state)
    return (time.perf_counter() - t0) / repeats, state


def timed_inner(body, state, inner: int = 32, outer: int = 3):
    """Per-iteration time of ``state = body(state)`` with ``inner``
    iterations folded into ONE compiled program (lax.fori_loop).

    On a remote-attached chip every program execution pays a tunnel round
    trip of tens of ms; a sub-ms program timed across dispatches measures
    the tunnel, not the chip. Folding the loop into the program amortizes
    that overhead to noise while the data dependency keeps the timing
    honest. Returns (seconds_per_inner_iter, final_state)."""
    prog = jax.jit(
        lambda s: jax.lax.fori_loop(0, inner, lambda i, t: body(t), s)
    )
    dt, state = timed_chain(prog, state, repeats=outer)
    return dt / inner, state


def mfu(achieved_flops: float):
    """achieved/peak for ONE chip, or None off-TPU."""
    from harmony_tpu.utils.platform import device_is_tpu, peak_bf16_flops

    d = jax.devices()[0]
    peak = peak_bf16_flops(d) if device_is_tpu(d) else None
    return round(achieved_flops / peak, 3) if peak else None


# ---------------------------------------------------------------------------
# Pod-launch harness shared by benchmarks/pod.py and tests/test_multihost.py
# ---------------------------------------------------------------------------

def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def sanitized_cpu_env(devices_per_proc: int) -> dict:
    """Child env for spawned pod/distributed workers: strip every TPU-claim
    var (PALLAS_AXON_POOL_IPS and AXON_* all trigger the experimental TPU
    client, which hangs backend init on a wedged transport) and force an
    n-virtual-device CPU backend."""
    import os

    env = dict(os.environ)
    for var in list(env):
        if var == "PALLAS_AXON_POOL_IPS" or var.startswith("AXON_"):
            env.pop(var)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    return env


def wait_for_ready(proc, deadline_s: float, marker: str = "READY") -> bool:
    """Read ``proc.stdout`` lines until ``marker`` (skipping benign startup
    prints), EOF, or the deadline. Each readline runs on a helper thread so
    a silently-wedged process hits the deadline instead of blocking
    forever."""
    import threading
    import time

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        box = {}
        t = threading.Thread(
            target=lambda: box.update(line=proc.stdout.readline()),
            daemon=True,
        )
        t.start()
        t.join(max(0.1, deadline - time.monotonic()))
        line = box.get("line", "")
        if line.strip() == marker:
            return True
        if not line:  # EOF: process exited without the marker
            return False
    return False
