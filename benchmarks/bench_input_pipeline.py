#!/usr/bin/env python
"""Input-pipeline benchmarks: prefetch micro-bench + input-service A/B.

Two modes, both host-bound on purpose (wide features, modest classes —
the benchmark measures the INPUT path, not the MXU; CPU backend, run
with JAX_PLATFORMS=cpu for a stable result):

  * default — the PR-1 micro-bench: ONE shuffling MLR job (shuffling
    forces real host work every epoch: the permutation gather +
    ``device_put`` the pipeline moves off the training thread) run twice
    at identical settings, ``input_prefetch`` off then on;
  * ``--service-ab`` — the multi-tenant input-service A/B: N tenant
    PROCESSES (the pod-follower / one-jobserver-per-job host shape —
    separate processes share no arrays, no devcache, no page locality)
    training on the SAME shuffling dataset, assembly in-process (every
    tenant process redoes the per-epoch permutation gather on the
    trainers' cores) vs through a STANDALONE input-service process (one
    shared assembly per epoch via the cross-tenant batch cache, batches
    over framed TCP, input work on the service's own cores — the
    disaggregation contract). Interleaved rounds with the arm order
    alternating, best-of per arm, and an in-bench bit-identical
    loss-parity gate per tenant per round.
    ``benchmarks/INPUT_SVC_r10.json`` is the committed capture.

Usage: python benchmarks/bench_input_pipeline.py [--n 8192] [--features
2048] [--epochs 6] [--batches 8] [--service-ab] [--tenants 3]
[--rounds 3] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_bench(
    n: int = 8192,
    features: int = 2048,
    classes: int = 16,
    epochs: int = 6,
    batches: int = 8,
    seed: int = 3,
) -> dict:
    """Run the A/B pair; returns the result dict (also usable from tests:
    tiny sizes keep it sub-second)."""
    import jax
    import numpy as np

    from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic
    from harmony_tpu.config.params import TrainerParams
    from harmony_tpu.dolphin import (
        TrainerContext,
        TrainingDataProvider,
        WorkerTasklet,
    )
    from harmony_tpu.metrics import MetricCollector, MetricManager
    from harmony_tpu.parallel.mesh import build_mesh
    from harmony_tpu.table import DenseTable, TableSpec

    mesh = build_mesh(jax.devices()[:1])
    x, y = make_synthetic(n, num_features=features, num_classes=classes,
                          seed=1)

    def one(prefetch: bool) -> "tuple[float, list, MetricManager]":
        trainer = MLRTrainer(
            num_classes=classes, num_features=features,
            features_per_partition=max(features // 8, 1), step_size=0.1,
        )
        params = TrainerParams(
            num_epochs=epochs, num_mini_batches=batches,
            comm_probe_period=0, input_prefetch=prefetch,
        )
        manager = MetricManager()
        manager.start_collection()
        table = DenseTable(TableSpec(trainer.model_table_config()), mesh)
        ctx = TrainerContext(params=params, model_table=table)
        # shuffling: real host assembly every epoch (the prefetch target),
        # same seed both runs so the batch streams are identical
        data = TrainingDataProvider([x, y], batches,
                                    shuffle_each_epoch=True, seed=seed)
        worker = WorkerTasklet(
            "bench-input", ctx, trainer, data, mesh,
            collector=MetricCollector(sink=manager.on_metric,
                                      job_id="bench-input", worker_id="w0"),
        )
        t0 = time.perf_counter()
        result = worker.run()
        wall = time.perf_counter() - t0
        return wall, result["losses"], manager

    # warmup pass compiles the step for both runs (shared progcache)
    one(False)
    wall_sync, losses_sync, _ = one(False)
    wall_pre, losses_pre, manager = one(True)

    total = epochs * (n // batches) * batches
    pipe = manager.input_pipeline_metrics(job_id="bench-input")
    out = {
        "metric": "input pipeline: sync vs prefetched dispatch (1 MLR job, "
                  "shuffling, cpu-sized)",
        "unit": "samples/sec",
        "sync": round(total / wall_sync, 1),
        "prefetched": round(total / wall_pre, 1),
        "speedup": round(wall_sync / wall_pre, 3),
        "losses_bit_identical": losses_sync == losses_pre,
        "pipeline": {
            "epochs_reported": len(pipe),
            "staged_batches": sum(m.staged_batches for m in pipe),
            "prefetch_hits": sum(m.prefetch_hits for m in pipe),
            "consumer_stall_sec": round(
                sum(m.consumer_stall_sec for m in pipe), 4),
            "producer_idle_sec": round(
                sum(m.producer_idle_sec for m in pipe), 4),
        },
        "config": {"n": n, "features": features, "classes": classes,
                   "epochs": epochs, "batches": batches},
    }
    return out


def _spawn_standalone_service(cache_mb: int = 768, pin_cores=None):
    """A standalone input-service process on an ephemeral port; returns
    (proc, (host, port)). The separate process is the honest
    disaggregation unit: its assembly work leaves the trainers' GIL and
    core share entirely. ``cache_mb`` sizes the cross-tenant cache so a
    few in-flight epochs fit (prefetch overlap keeps ~2 epochs live per
    tenant; an undersized cache degrades to per-tenant assembly);
    ``pin_cores`` pins the service to its own host cores
    (HARMONY_INPUT_PIN_CORES — input capacity scaled separately from
    the trainers', which is the point of disaggregating)."""
    env = dict(os.environ)
    env.setdefault("HARMONY_INPUT_CACHE_MB", str(cache_mb))
    if pin_cores:
        env["HARMONY_INPUT_PIN_CORES"] = ",".join(str(c) for c in pin_cores)
    proc = subprocess.Popen(
        [sys.executable, "-m", "harmony_tpu.inputsvc", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env,
    )
    line = proc.stdout.readline()
    info = json.loads(line)
    return proc, (info["host"], int(info["port"]))


def tenant_worker_main(cfg_json: str) -> int:
    """``--tenant-worker`` entry: ONE tenant process of the service A/B.

    Builds and compile-warms everything shape-dependent on a zeros
    dataset (program-cache keys are structural, so the measured run
    reuses the compiled programs), signals READY, then on GO runs the
    REAL job — dataset materialization, per-epoch assembly (or service
    fetch) and training are all inside the measured window, exactly the
    work a fresh tenant process pays."""
    import numpy as np

    cfg = json.loads(cfg_json)
    import jax

    from harmony_tpu import inputsvc
    from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic
    from harmony_tpu.config.params import TrainerParams
    from harmony_tpu.dolphin import (
        DeferredTrainingDataProvider,
        TrainerContext,
        TrainingDataProvider,
        WorkerTasklet,
    )
    from harmony_tpu.parallel.mesh import build_mesh
    from harmony_tpu.table import DenseTable, TableSpec

    n, feats, classes = cfg["n"], cfg["features"], cfg["classes"]
    batches, epochs, seed = cfg["batches"], cfg["epochs"], cfg["seed"]
    mesh = build_mesh(jax.devices()[:1])

    def build_worker(data, feed, num_epochs):
        trainer = MLRTrainer(
            num_classes=classes, num_features=feats,
            features_per_partition=max(feats // 8, 1), step_size=0.1,
        )
        params = TrainerParams(num_epochs=num_epochs,
                               num_mini_batches=batches,
                               comm_probe_period=0)
        table = DenseTable(TableSpec(trainer.model_table_config()), mesh)
        ctx = TrainerContext(params=params, model_table=table)
        return WorkerTasklet(cfg["tenant"], ctx, trainer, data, mesh,
                             input_feed=feed)

    warm = TrainingDataProvider(
        [np.zeros((n, feats), np.float32), np.zeros(n, np.int32)],
        batches, shuffle_each_epoch=False,
    )
    build_worker(warm, None, 1).run()
    print("READY", flush=True)
    sys.stdin.readline()  # GO

    t0 = time.perf_counter()
    data_args = cfg["data_args"]
    feed = None
    if cfg.get("endpoint"):
        # service tenant: the local dataset exists only as the fallback
        # source — defer its materialization (the data_fn call is the
        # single most expensive host step) until a fallback needs it
        data = DeferredTrainingDataProvider(
            lambda: make_synthetic(**data_args), n, batches,
            shuffle_each_epoch=True, seed=seed,
            array_specs=[((feats,), "float32"), ((), "int32")],
        )
        spec = inputsvc.DatasetSpec.build(
            "harmony_tpu.apps.mlr:make_synthetic", data_args,
            lo=0, hi=n, num_mini_batches=batches, shuffle=True, seed=seed,
        )
        feed = inputsvc.TrainerInputFeed(
            spec, data, tenant=cfg["tenant"],
            endpoint=(cfg["endpoint"][0], int(cfg["endpoint"][1])),
        )
    else:
        x, y = make_synthetic(**data_args)
        data = TrainingDataProvider([x, y], batches,
                                    shuffle_each_epoch=True, seed=seed)
    result = build_worker(data, feed, epochs).run()
    out = {"wall": time.perf_counter() - t0, "losses": result["losses"]}
    if feed is not None:
        out["feed"] = feed.stats()
    print(json.dumps(out), flush=True)
    return 0


def run_service_bench(
    tenants: int = 8,
    n: int = 2097152,
    features: int = 4,
    classes: int = 2,
    epochs: int = 2,
    batches: int = 8,
    seed: int = 3,
    rounds: int = 3,
    standalone: bool = True,
    cores: int = 2,
    service_cores: int = 2,
) -> dict:
    """Multi-tenant service-vs-in-process A/B (see module docstring).
    Returns the result dict; tiny sizes keep it test-runnable.

    Tenants are PROCESSES: separate trainer processes share no arrays,
    no page-cache locality and no in-process devcache — each one pays
    its own dataset materialization and its own per-epoch permutation
    gather, which is the duplicated host work the service exists to
    deduplicate (same-process tenants already share host arrays through
    the jobserver's host-data cache, and their concurrent same-pattern
    gathers even share CPU cache — measuring THAT shape undersells
    nothing because the framework already solved it).

    Shapes are tall and NARROW (2M x 4): per byte, a permutation gather
    of 16-byte rows costs ~5 memcpys (random access), the same
    assembly-per-byte asymmetry real input pipelines have. The default
    tenant mix — MANY short same-dataset jobs — is the hyperparameter-
    sweep shape, where per-tenant dataset materialization plus the
    early epochs' assembly dominate and disaggregation pays most;
    longer-epoch mixes taper toward parity as the per-epoch wire cost
    approaches the per-epoch gather cost on a byte-bound host (run
    ``--epochs 4`` to see the taper — the committed JSON records it).

    Core budgets: ``cores`` pins the parent — and so every spawned
    tenant process — to the trainers' budget; ``service_cores`` gives
    the standalone service its OWN cores (HARMONY_INPUT_PIN_CORES),
    which is the disaggregation contract: input capacity scales
    independently of the trainers'. The in-process arm cannot use those
    extra cores BY CONSTRUCTION — in-process assembly runs inside the
    trainer processes; that asymmetry is the deployment reality being
    measured, and the result records both budgets."""
    all_cores = (sorted(os.sched_getaffinity(0))
                 if hasattr(os, "sched_getaffinity") else [])
    old_affinity = None
    svc_pin = None
    if cores and all_cores:
        old_affinity = set(all_cores)
        trainer_set = set(all_cores[:max(1, cores)])
        svc_pin = all_cores[max(1, cores):max(1, cores) + service_cores]
        os.sched_setaffinity(0, trainer_set)  # children inherit
    samples_per_tenant = epochs * (n // batches) * batches
    data_args = {"n": n, "num_features": features, "num_classes": classes,
                 "seed": 1}
    me = os.path.abspath(__file__)

    def run_arm(endpoint, round_seed: int):
        """One arm: ``tenants`` concurrent tenant PROCESSES. endpoint=
        None -> in-process assembly; else the service feed. The wall
        clock covers GO -> last result (materialization + assembly/
        fetch + training), not process spawn or compile warmup.
        Returns (wall_sec, losses per tenant)."""
        procs = []
        for i in range(tenants):
            cfg = {
                "n": n, "features": features, "classes": classes,
                "batches": batches, "epochs": epochs, "seed": round_seed,
                "tenant": f"t{i}", "data_args": data_args,
                "endpoint": list(endpoint) if endpoint else None,
            }
            wenv = dict(os.environ)
            # hold ~3 epochs of fetched batches (live epoch + the
            # prespawned next + slack): an undersized client cache
            # evicts live entries and turns shared reads into misses
            wenv.setdefault(
                "HARMONY_INPUT_CLIENT_CACHE_MB",
                str(max(256, 4 * (n * (features + 1) * 4 >> 20))))
            procs.append(subprocess.Popen(
                [sys.executable, me, "--tenant-worker", json.dumps(cfg)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
                env=wenv,
            ))
        try:
            for p in procs:
                line = p.stdout.readline()
                if line.strip() != "READY":
                    raise RuntimeError(f"tenant worker died: {line!r}")
            t0 = time.perf_counter()
            for p in procs:
                p.stdin.write("GO\n")
                p.stdin.flush()
            outs = [json.loads(p.stdout.readline()) for p in procs]
            wall = time.perf_counter() - t0
        finally:
            # terminate ALL first, then reap with kill escalation: a
            # wedged worker must not leave its siblings orphaned (still
            # pinned to the trainer cores) or mask the original error
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)
        return wall, [o["losses"] for o in outs]

    from harmony_tpu import inputsvc  # jax-free import (client side only)

    svc_proc = None
    svc = None
    # the service accumulates every round's epochs (fresh keys per
    # round): size its cache so LIVE epochs never churn against dead
    # rounds' entries
    svc_cache_mb = max(768, (3 * rounds + 6) * epochs
                       * (n * (features + 1) * 4 >> 20) // 2)
    if standalone:
        svc_proc, endpoint = _spawn_standalone_service(
            cache_mb=svc_cache_mb, pin_cores=svc_pin)
    else:
        svc = inputsvc.InputService()
        endpoint = ("127.0.0.1", svc.start())
    try:
        # service warmup: one-time costs (its data_fn import + dataset
        # materialization) land outside the timed rounds; the tenant
        # processes warm their own compiles before READY
        run_arm(endpoint, seed - 1)
        best = {"inproc": 0.0, "service": 0.0}
        parity = True
        for r in range(rounds):
            round_seed = seed + 1000 * r  # fresh epoch keys every round
            arms = (("inproc", None), ("service", endpoint))
            if r % 2:  # alternate order: neither arm owns the warm cache
                arms = arms[::-1]
            losses: dict = {}
            for name, ep in arms:
                wall, tenant_losses = run_arm(ep, round_seed)
                losses[name] = tenant_losses
                sps = tenants * samples_per_tenant / wall
                best[name] = max(best[name], sps)
                print(f"  round {r} {name}: wall {wall:.2f}s "
                      f"({sps:,.0f} samples/s)", file=sys.stderr)
            parity = parity and losses["inproc"] == losses["service"]
        stats = inputsvc.fetch_stats(endpoint)
    finally:
        if svc_proc is not None:
            svc_proc.terminate()
            svc_proc.wait(timeout=10)
        if svc is not None:
            svc.stop()
        if old_affinity is not None:
            os.sched_setaffinity(0, old_affinity)
    return {
        "metric": f"input service: {tenants} same-dataset shuffling MLR "
                  "tenant processes, service vs in-process assembly "
                  "(cpu bench)",
        "unit": "aggregate samples/sec",
        "inproc_sps": round(best["inproc"], 1),
        "service_sps": round(best["service"], 1),
        "speedup": round(best["service"] / best["inproc"], 3)
        if best["inproc"] else None,
        "losses_bit_identical": parity,
        "service": {
            "mode": "standalone process" if standalone else "embedded",
            "batches_from_cache": stats["batches_from_cache"],
            "batches_assembled": stats["batches_assembled"],
            "cache": {k: stats["cache"][k]
                      for k in ("hits", "misses", "evictions")},
            "workers": stats["workers"],
        },
        "note": "honest core budgets: tenant processes pinned to "
                "config.cores trainer cores in BOTH arms; the service "
                "arm additionally spends config.service_cores on its "
                "own input-worker process (HARMONY_INPUT_PIN_CORES) — "
                "scaling input on separate cores IS the disaggregation "
                "being measured, and the in-process arm cannot use "
                "those cores by construction (its assembly runs inside "
                "the trainer processes). The win: tenant processes "
                "share one epoch assembly through the cross-tenant "
                "cache instead of each redoing the permutation gather "
                "of a dataset only it can see",
        "config": {"tenants": tenants, "n": n, "features": features,
                   "classes": classes, "epochs": epochs,
                   "batches": batches, "rounds": rounds,
                   "cores": cores, "service_cores": service_cores},
    }


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    # size defaults differ per mode: the micro-bench wants wide rows
    # (device_put-heavy), the service A/B wants tall-narrow (assembly-
    # compute-heavy — see run_service_bench)
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--features", type=int, default=None)
    ap.add_argument("--classes", type=int, default=None)
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--batches", type=int, default=8)
    ap.add_argument("--service-ab", action="store_true",
                    help="multi-tenant service-vs-in-process A/B instead "
                         "of the single-job prefetch micro-bench")
    ap.add_argument("--tenants", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--cores", type=int, default=2,
                    help="service-ab: trainer-core budget every tenant "
                         "process is pinned to, both arms (0 = none)")
    ap.add_argument("--service-cores", type=int, default=2,
                    help="service-ab: input-worker cores the standalone "
                         "service pins itself to, OUTSIDE the trainer "
                         "budget (the disaggregation contract)")
    ap.add_argument("--tenant-worker", default=None, metavar="CFG_JSON",
                    help=argparse.SUPPRESS)  # internal: one A/B tenant
    ap.add_argument("--embedded", action="store_true",
                    help="service-ab: run the service in-process instead "
                         "of as a standalone worker process")
    ap.add_argument("--json", action="store_true",
                    help="print only the JSON line")
    args = ap.parse_args(argv)
    if args.tenant_worker:
        sys.exit(tenant_worker_main(args.tenant_worker))
    if args.service_ab:
        res = run_service_bench(
            tenants=args.tenants,
            n=args.n if args.n is not None else 2097152,
            features=args.features if args.features is not None else 4,
            classes=args.classes if args.classes is not None else 2,
            epochs=args.epochs if args.epochs is not None else 2,
            batches=args.batches,
            rounds=args.rounds, standalone=not args.embedded,
            cores=args.cores, service_cores=args.service_cores,
        )
        if not args.json:
            print(f"  inproc {res['inproc_sps']:,} vs service "
                  f"{res['service_sps']:,} aggregate samples/sec -> "
                  f"{res['speedup']}x (parity="
                  f"{res['losses_bit_identical']})", file=sys.stderr)
    else:
        res = run_bench(n=args.n if args.n is not None else 8192,
                        features=(args.features if args.features is not None
                                  else 2048),
                        classes=args.classes if args.classes is not None
                        else 16,
                        epochs=args.epochs if args.epochs is not None else 6,
                        batches=args.batches)
        if not args.json:
            print(f"  sync {res['sync']:,} vs prefetched "
                  f"{res['prefetched']:,} samples/sec -> "
                  f"{res['speedup']}x", file=sys.stderr)
    print(json.dumps(res))
    return res


if __name__ == "__main__":
    main()
