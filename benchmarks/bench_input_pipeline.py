#!/usr/bin/env python
"""Micro-benchmark: synchronous vs prefetched input dispatch.

Isolates the asynchronous host→device input pipeline (dolphin/prefetch.py)
from the multi-tenant headline bench: ONE shuffling MLR job — shuffling
forces the per-batch path with real host work every epoch (the gather +
``device_put`` that the pipeline moves off the training thread) — run twice
at identical settings, ``input_prefetch`` off then on. Reports samples/sec
for both, the speedup, and the pipeline's own per-epoch counters (stall =
the training thread waited on input; idle = the producer ran ahead).

Shapes are host-bound on purpose (wide features, modest classes): the
benchmark measures the INPUT path, not the MXU. CPU backend; run with
JAX_PLATFORMS=cpu for a stable result.

Usage: python benchmarks/bench_input_pipeline.py [--n 8192] [--features
2048] [--epochs 6] [--batches 8] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_bench(
    n: int = 8192,
    features: int = 2048,
    classes: int = 16,
    epochs: int = 6,
    batches: int = 8,
    seed: int = 3,
) -> dict:
    """Run the A/B pair; returns the result dict (also usable from tests:
    tiny sizes keep it sub-second)."""
    import jax
    import numpy as np

    from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic
    from harmony_tpu.config.params import TrainerParams
    from harmony_tpu.dolphin import (
        TrainerContext,
        TrainingDataProvider,
        WorkerTasklet,
    )
    from harmony_tpu.metrics import MetricCollector, MetricManager
    from harmony_tpu.parallel.mesh import build_mesh
    from harmony_tpu.table import DenseTable, TableSpec

    mesh = build_mesh(jax.devices()[:1])
    x, y = make_synthetic(n, num_features=features, num_classes=classes,
                          seed=1)

    def one(prefetch: bool) -> "tuple[float, list, MetricManager]":
        trainer = MLRTrainer(
            num_classes=classes, num_features=features,
            features_per_partition=max(features // 8, 1), step_size=0.1,
        )
        params = TrainerParams(
            num_epochs=epochs, num_mini_batches=batches,
            comm_probe_period=0, input_prefetch=prefetch,
        )
        manager = MetricManager()
        manager.start_collection()
        table = DenseTable(TableSpec(trainer.model_table_config()), mesh)
        ctx = TrainerContext(params=params, model_table=table)
        # shuffling: real host assembly every epoch (the prefetch target),
        # same seed both runs so the batch streams are identical
        data = TrainingDataProvider([x, y], batches,
                                    shuffle_each_epoch=True, seed=seed)
        worker = WorkerTasklet(
            "bench-input", ctx, trainer, data, mesh,
            collector=MetricCollector(sink=manager.on_metric,
                                      job_id="bench-input", worker_id="w0"),
        )
        t0 = time.perf_counter()
        result = worker.run()
        wall = time.perf_counter() - t0
        return wall, result["losses"], manager

    # warmup pass compiles the step for both runs (shared progcache)
    one(False)
    wall_sync, losses_sync, _ = one(False)
    wall_pre, losses_pre, manager = one(True)

    total = epochs * (n // batches) * batches
    pipe = manager.input_pipeline_metrics(job_id="bench-input")
    out = {
        "metric": "input pipeline: sync vs prefetched dispatch (1 MLR job, "
                  "shuffling, cpu-sized)",
        "unit": "samples/sec",
        "sync": round(total / wall_sync, 1),
        "prefetched": round(total / wall_pre, 1),
        "speedup": round(wall_sync / wall_pre, 3),
        "losses_bit_identical": losses_sync == losses_pre,
        "pipeline": {
            "epochs_reported": len(pipe),
            "staged_batches": sum(m.staged_batches for m in pipe),
            "prefetch_hits": sum(m.prefetch_hits for m in pipe),
            "consumer_stall_sec": round(
                sum(m.consumer_stall_sec for m in pipe), 4),
            "producer_idle_sec": round(
                sum(m.producer_idle_sec for m in pipe), 4),
        },
        "config": {"n": n, "features": features, "classes": classes,
                   "epochs": epochs, "batches": batches},
    }
    return out


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--features", type=int, default=2048)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batches", type=int, default=8)
    ap.add_argument("--json", action="store_true",
                    help="print only the JSON line")
    args = ap.parse_args(argv)
    res = run_bench(n=args.n, features=args.features, classes=args.classes,
                    epochs=args.epochs, batches=args.batches)
    if not args.json:
        print(f"  sync {res['sync']:,} vs prefetched {res['prefetched']:,} "
              f"samples/sec -> {res['speedup']}x", file=sys.stderr)
    print(json.dumps(res))
    return res


if __name__ == "__main__":
    main()
