#!/usr/bin/env python
"""Multi-run fairness series — the honest artifact.

The 1-core host makes single fairness runs noisy (the cheapest tenant's
~1s isolated wall turns any background blip into a slowdown spike), and
round 3 was called out for quoting a best-of as if it were the artifact.
This driver runs benchmarks/fairness.py N times back to back, records
EVERY run, and embeds the MEDIAN-max_slowdown run as the representative
— median, never min — plus the full per-run (jain, max_slowdown) series
so the spread is visible in the artifact itself.

Writes benchmarks/FAIRNESS_<suffix>.json; prints ONE JSON line (summary).
Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python benchmarks/fairness_series.py [N] [suffix]
"""
import json
import os
import statistics
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    suffix = sys.argv[2] if len(sys.argv) > 2 else "r05"
    out_path = os.path.join(HERE, f"FAIRNESS_{suffix}.json")
    runs = []
    for i in range(n):
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "fairness.py")],
            capture_output=True, text=True, timeout=1200,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout else ""
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            d = {"error": f"run {i}: no JSON ({proc.stderr[-300:]})"}
        runs.append(d)
        sa = d.get("share_all", {})
        print(f"run {i + 1}/{n}: jain={sa.get('jain')} "
              f"max={sa.get('max_slowdown')}", file=sys.stderr)
    ok = [r for r in runs if "share_all" in r]
    if not ok:
        out = {"metric": "multi-tenant fairness (series)", "value": None,
               "error": "no successful runs", "runs": runs}
        print(json.dumps(out))
        return
    maxes = sorted(r["share_all"]["max_slowdown"] for r in ok)
    med_max = maxes[len(maxes) // 2]
    rep = next(r for r in ok if r["share_all"]["max_slowdown"] == med_max)
    out = {
        "metric": "multi-tenant fairness (share_all, N-run series)",
        "unit": "jain index over per-job slowdowns",
        "runs_total": n, "runs_ok": len(ok),
        "series": [
            {"jain": r["share_all"]["jain"],
             "max_slowdown": r["share_all"]["max_slowdown"]}
            for r in ok
        ],
        "median_max_slowdown": med_max,
        "median_jain": round(statistics.median(
            r["share_all"]["jain"] for r in ok), 3),
        "representative_run": rep,
        "value": round(statistics.median(
            r["share_all"]["jain"] for r in ok), 3),
        "note": (
            "representative_run is the MEDIAN-max_slowdown run, never the "
            "best; the full series is recorded above. The cheapest "
            "tenant's slowdown floor on this serialized 1-core backend is "
            "~own_work + units x peer_unit_residual; the anticipatory "
            "hold + peer-sized grouping put the typical run at ~2.9-3.3x "
            "(was 15x in round 2, 4.0x in round 3)."
        ),
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
