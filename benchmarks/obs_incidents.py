"""Incident-detection scorecard: PR 18's seeded chaos schedules through
the real stack, scored against the injected-fault ground truth.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/obs_incidents.py > benchmarks/OBS_INCIDENT_r19.json

Each fault cell replays one committed ``(seed, scenario, intensity)``
schedule from the chaos sweep with a live IncidentEngine correlating
beside it (a ticker thread stands in for the jobserver scrape cycle, so
joblog evidence is harvested before the scenario's teardown clears it).
The schedule's fired fault sites are the ground truth; an incident
counts as a detection when its causal chain names an injected site.

Scored per fault class (the SITE_CATALOG layer of the fired site):

- ``recall_by_class`` / ``recall`` — injected class-instances whose
  sites some incident chain named; the acceptance floor is 0.9.
- ``precision`` — attributed incidents / all incidents over fault cells.
- ``false_positives_control`` — incidents raised on the healthy control
  arm (an unfaulted JobServer + tenant job); the floor is exactly 0.
- ``mttd_s`` / ``mttr_s`` — detection and resolution latency
  distributions over every incident the sweep produced.

``--quick`` skips the HA takeover scenarios (the slow tier), mirroring
benchmarks/chaos_sweep.py.
"""
import argparse
import json
import logging
import os
import statistics
import sys
import tempfile
import threading
import time

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from harmony_tpu.faults import chaos  # noqa: E402
from harmony_tpu.jobserver import joblog  # noqa: E402
from harmony_tpu.metrics.incidents import IncidentEngine  # noqa: E402
from harmony_tpu.tracing import flight  # noqa: E402

#: correlation window for the sweep — short so quiescence resolution
#: (and therefore MTTR) lands inside one cell instead of the production
#: default 120 s
WINDOW_SEC = 2.0

#: one cell per scenario class, seeds shared with the committed
#: chaos-sweep capture so each schedule replays byte-identically
GRID = [
    (11, "halog_enospc", 0.5),
    (3, "halog_torn_write", 0.5),
    (4, "log_slow_fsync", 0.5),
    (11, "client_partition", 0.5),
    (3, "lease_disk_flap", 0.5),
    (5, "chkp_torn_block", 0.6),
    (8, "chkp_bitrot_read", 0.6),
    (5, "chkp_enospc_commit", 0.6),
    (11, "repl_partition_heal", 0.5),
    (21, "partition_during_takeover", 0.5),
    (22, "overload_storm_leader_kill", 0.5),
]

#: fired site -> fault class, from the chaos site catalog
_SITE_CLASS = {site: layer
               for layer, sites in chaos.SITE_CATALOG.items()
               for site in sites}


def _site_class(site: str) -> str:
    return _SITE_CLASS.get(site, site.split(".", 1)[0])


def _pctl(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return round(xs[idx], 4)


def _dist(xs):
    return {"n": len(xs), "p50": _pctl(xs, 0.50), "p99": _pctl(xs, 0.99),
            "max": _pctl(xs, 1.0),
            "mean": round(statistics.fmean(xs), 4) if xs else None}


class _Ticker:
    """Background correlate loop — the scrape cycle's stand-in, so the
    engine sees joblog evidence live (scenario teardown clears it)."""

    def __init__(self, engine: IncidentEngine, period: float = 0.25) -> None:
        self.engine = engine
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(period,), daemon=True,
            name="obs-incidents-ticker")

    def _run(self, period: float) -> None:
        while not self._stop.wait(period):
            try:
                self.engine.correlate()
            except Exception:
                pass

    def __enter__(self) -> "_Ticker":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def _fresh_engine() -> IncidentEngine:
    """A per-cell engine over a clean evidence plane. persist=False: the
    scorecard's engine must not feed its own verdicts back into the
    joblog it harvests."""
    flight.reset_recorder()
    flight.get_recorder()
    joblog.clear_events()
    return IncidentEngine(window_sec=WINDOW_SEC, persist=False)


def _incident_sites(inc: dict) -> set:
    sites = set()
    if inc.get("site"):
        sites.add(str(inc["site"]))
    for edge in inc.get("chain") or []:
        if edge.get("site"):
            sites.add(str(edge["site"]))
    return sites


def _drain(engine: IncidentEngine) -> list:
    """Final harvest + quiescence pass: anything still open resolves as
    ``quiesced`` with a deterministic MTTR of one window."""
    engine.correlate()
    engine.correlate(now=time.time() + WINDOW_SEC + 0.5)
    return engine.recent(limit=128)


def run_fault_cell(seed: int, scenario: str, intensity: float) -> dict:
    engine = _fresh_engine()
    with tempfile.TemporaryDirectory(prefix="harmony-obsinc-") as td:
        with _Ticker(engine):
            report = chaos.run_scenario(seed, intensity=intensity,
                                        scenario=scenario, workdir=td)
    incidents = _drain(engine)

    injected = sorted({k.split(":", 1)[0] for a in report["acts"]
                       for k in (a.get("fault_fires") or {})})
    named = {s for inc in incidents for s in _incident_sites(inc)}
    matched = sorted(s for s in injected if s in named)
    attributed = sum(1 for inc in incidents
                     if _incident_sites(inc) & set(injected))
    return {
        "seed": seed,
        "scenario": scenario,
        "intensity": intensity,
        "ok": report["ok"],
        "injected_sites": injected,
        "injected_classes": sorted({_site_class(s) for s in injected}),
        "matched_sites": matched,
        "detected_classes": sorted({_site_class(s) for s in matched}),
        "incidents": len(incidents),
        "attributed": attributed,
        "mttd_s": [round(inc["mttd_sec"], 4) for inc in incidents
                   if inc.get("mttd_sec") is not None],
        "mttr_s": [round(inc["mttr_sec"], 4) for inc in incidents
                   if inc.get("mttr_sec") is not None],
        "wall_s": report["wall_s"],
    }


def run_control_cell() -> dict:
    """The healthy arm: a real JobServer runs one tenant job to
    completion with no fault plan armed. Any incident here is a false
    positive — the acceptance floor is zero."""
    from harmony_tpu.jobserver.server import JobServer

    engine = _fresh_engine()
    t0 = time.monotonic()
    with _Ticker(engine):
        server = JobServer(num_executors=2)
        try:
            server.start()
            fut = server.submit(chaos.tiny_job("control-healthy"))
            result = fut.result(timeout=300)
        finally:
            server.shutdown(timeout=60.0)
    incidents = _drain(engine)
    return {
        "scenario": "healthy_control",
        "ok": bool(result.get("losses")),
        "incidents": len(incidents),
        "false_positives": len(incidents),
        "incident_kinds": sorted({i.get("trigger_kind") or "?"
                                  for i in incidents}),
        "wall_s": round(time.monotonic() - t0, 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="skip the HA takeover scenarios (the slow tier)")
    args = ap.parse_args()
    logging.disable(logging.ERROR)  # chaos storms are LOUD by design

    grid = [(s, name, i) for s, name, i in GRID
            if not (args.quick and name in chaos.HA_SCENARIOS)]

    doc = {
        "metric": "obs_incidents",
        "unit": "recall / precision / seconds",
        "mode": ("seeded chaos schedules with a live incident engine "
                 "correlating beside the run; fired fault sites are the "
                 "ground truth, incident causal chains are the "
                 "detections; healthy JobServer control arm for the "
                 "false-positive floor"),
        "config": {
            "window_sec": WINDOW_SEC,
            "grid_cells": len(grid),
            "acceptance": {"recall_floor": 0.9,
                           "control_false_positives": 0},
        },
        "grid": [],
    }
    t_sweep = time.monotonic()
    injected_n = detected_n = 0
    by_class: dict = {}
    incidents_total = attributed_total = 0
    mttd_all: list = []
    mttr_all: list = []
    for seed, name, intensity in grid:
        print(f"# {name} seed={seed} i={intensity} ...", file=sys.stderr)
        t0 = time.monotonic()
        try:
            cell = run_fault_cell(seed, name, intensity)
        except Exception as exc:  # a crashed cell is a red cell
            cell = {"seed": seed, "scenario": name, "intensity": intensity,
                    "ok": False, "error": repr(exc),
                    "injected_classes": [], "detected_classes": [],
                    "incidents": 0, "attributed": 0,
                    "mttd_s": [], "mttr_s": []}
        cell["cell_wall_s"] = round(time.monotonic() - t0, 1)
        doc["grid"].append(cell)
        for cls in cell["injected_classes"]:
            hit = cls in cell["detected_classes"]
            injected_n += 1
            detected_n += 1 if hit else 0
            agg = by_class.setdefault(cls, {"injected": 0, "detected": 0})
            agg["injected"] += 1
            agg["detected"] += 1 if hit else 0
        incidents_total += cell["incidents"]
        attributed_total += cell["attributed"]
        mttd_all.extend(cell["mttd_s"])
        mttr_all.extend(cell["mttr_s"])
        print(f"#   injected={cell.get('injected_sites')} "
              f"matched={cell.get('matched_sites')} "
              f"incidents={cell['incidents']} "
              f"wall={cell['cell_wall_s']}s", file=sys.stderr)

    print("# healthy_control ...", file=sys.stderr)
    try:
        control = run_control_cell()
    except Exception as exc:
        control = {"scenario": "healthy_control", "ok": False,
                   "error": repr(exc), "incidents": -1,
                   "false_positives": -1}
    doc["control"] = control
    print(f"#   false_positives={control['false_positives']}",
          file=sys.stderr)

    recall = round(detected_n / injected_n, 4) if injected_n else None
    doc["summary"] = {
        "recall": recall,
        "recall_by_class": {
            cls: round(agg["detected"] / agg["injected"], 4)
            for cls, agg in sorted(by_class.items())},
        "precision": (round(attributed_total / incidents_total, 4)
                      if incidents_total else None),
        "incidents_total": incidents_total,
        "attributed_total": attributed_total,
        "false_positives_control": control["false_positives"],
        "mttd_s": _dist(mttd_all),
        "mttr_s": _dist(mttr_all),
        "sweep_wall_s": round(time.monotonic() - t_sweep, 1),
    }
    print(json.dumps(doc, indent=1))
    ok = (recall is not None and recall >= 0.9
          and control["false_positives"] == 0
          and all(c["ok"] for c in doc["grid"]))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
