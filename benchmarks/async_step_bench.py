#!/usr/bin/env python
"""Bounded-staleness async step — overlap push/pull with compute.

One MLR WorkerTasklet workload measured in four arms under an injected
``worker.pull`` comm delay (FaultRule action="delay": a slow link), with
the sparse_step_bench methodology (interleaved rounds, best-of per arm,
an in-bench parity assertion before any number is reported):

  * ``sync``     — the host-driven unfused baseline (pull -> comp ->
    push serialized on the training thread; the delay is exposed on the
    critical path every batch);
  * ``async b=0`` — AsyncStepDriver with staleness bound 0: same
    programs, same apply order, fully serialized by the staleness gate —
    the BIT-IDENTICAL control arm (asserted in-bench against ``sync``);
  * ``async b=1`` / ``async b=2`` — the overlap arms: step k+1's compute
    runs while the comm thread drains step k's push + k+1's pull, so the
    injected delay moves off the critical path (bounded by the window).

Quality is reported honestly: per-epoch losses for every arm (staleness
reorders nothing at bound 0; at bound >= 1 updates apply against a view
up to ``bound`` deltas stale, so the curves may differ — they are
committed as measured, not asserted equal).

CPU-backend honesty note: compute and comm here share ~2 host cores, so
the overlap win is bounded by the injected sleep (a sleep yields the
GIL/cores; real D2H/H2D transfer time would too, but a real TPU also
overlaps the device-side collective with the next step's MXU work,
which this bench cannot see).

Writes benchmarks/ASYNC_STEP_r16.json and prints ONE JSON line.
Run: python benchmarks/async_step_bench.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

ROUNDS = 3

# MLR shape: enough compute per batch that overlap has something to hide
# the injected comm delay behind (comp ~ comm is the interesting regime;
# when either side dominates, overlap can only save the smaller one).
N, FEATURES, CLASSES, FPP = 4096, 2048, 64, 256
EPOCHS, BATCHES = 3, 8
PULL_DELAY_SEC = 0.004  # injected per-batch "slow link" on worker.pull

ARMS = (
    ("sync", False, 0),
    ("async_b0", True, 0),
    ("async_b1", True, 1),
    ("async_b2", True, 2),
)


def run_arm(async_on: bool, bound: int, *, n=None, features=None,
            classes=None, fpp=None, epochs=None, batches=None,
            delay=None):
    """One full training run; returns (steps_per_sec, losses, stats).

    Shape/delay kwargs default to the module constants; bench.py's
    ``measure_async_step`` hook passes a smaller probe shape."""
    n = N if n is None else n
    features = FEATURES if features is None else features
    classes = CLASSES if classes is None else classes
    fpp = FPP if fpp is None else fpp
    epochs = EPOCHS if epochs is None else epochs
    batches = BATCHES if batches is None else batches
    delay = PULL_DELAY_SEC if delay is None else delay
    from harmony_tpu import faults
    from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic
    from harmony_tpu.config.params import TrainerParams
    from harmony_tpu.dolphin import (
        TrainerContext,
        TrainingDataProvider,
        WorkerTasklet,
    )
    from harmony_tpu.faults.plan import FaultPlan, FaultRule
    from harmony_tpu.parallel import build_mesh
    from harmony_tpu.table import DenseTable, TableSpec

    mesh = build_mesh(jax.devices("cpu")[:1])
    trainer = MLRTrainer(num_classes=classes, num_features=features,
                         features_per_partition=fpp)
    table = DenseTable(TableSpec(trainer.model_table_config()), mesh)
    params = TrainerParams(num_epochs=epochs, num_mini_batches=batches,
                           fused_step=False, async_step=async_on,
                           staleness_bound=bound)
    ctx = TrainerContext(params=params, model_table=table)
    data = TrainingDataProvider(
        make_synthetic(n, features, classes, seed=16), batches)
    w = WorkerTasklet(f"async-bench-{async_on}-{bound}", ctx, trainer,
                      data, mesh)
    # the slow link fires on whichever thread performs the pull: the
    # training thread (sync — exposed) or the comm thread (async —
    # overlapped up to the staleness window)
    faults.arm(FaultPlan([FaultRule("worker.pull", action="delay",
                                    delay_sec=delay, count=-1)]))
    try:
        t0 = time.perf_counter()
        result = w.run()
        dt = time.perf_counter() - t0
    finally:
        faults.disarm()
    stats = {}
    stats_fn = getattr(w._step, "staleness_stats", None)
    if stats_fn is not None:
        s = stats_fn()
        stats = {"max_lag": s["max_lag"],
                 "exposed_wait_s": round(s["exposed_wait_sec"], 4),
                 "overlapped_comm_s": round(s["overlapped_comm_sec"], 4)}
    split = getattr(w._step, "mean_phase_seconds", None)
    if split is not None:
        p, c, q = split()
        stats["mean_phase_s"] = {"pull": round(p, 5), "comp": round(c, 5),
                                 "push": round(q, 5)}
    return epochs * batches / dt, result["losses"], stats


def main() -> None:
    best = {name: 0.0 for name, _, _ in ARMS}
    stats = {name: {} for name, _, _ in ARMS}
    losses = {}
    for _ in range(ROUNDS):
        # interleave arms inside every round (host throughput drifts
        # round to round), best-of per arm
        for name, async_on, bound in ARMS:
            sps, arm_losses, st = run_arm(async_on, bound)
            if bound == 0 and name in losses:
                # only the serialized arms are run-to-run deterministic;
                # at bound >= 1 the view lag anywhere in [0, bound] is
                # timing-dependent, so those curves legitimately vary
                assert arm_losses == losses[name], (
                    f"{name}: nondeterministic losses within one arm")
            if bound == 0 or sps > best[name]:
                losses[name] = arm_losses
            if sps > best[name]:
                best[name] = sps
                stats[name] = st
    # the parity gate: bound 0 only counts if it learns EXACTLY what the
    # synchronous path learns (same programs, same apply order)
    assert losses["async_b0"] == losses["sync"], (
        "staleness-0 parity broke: "
        f"{losses['async_b0'][:3]} vs {losses['sync'][:3]}")
    arms = {}
    for name, _, bound in ARMS:
        arms[name] = {
            "steps_per_sec": round(best[name], 2),
            "speedup_vs_sync": round(best[name] / best["sync"], 2),
            "staleness_bound": bound,
            **stats[name],
        }
    out = {
        "metric": "async_step",
        "unit": "steps/sec",
        "rounds": ROUNDS,
        "mode": "interleaved arms, best-of per arm, in-bench staleness-0 "
                "bit-identical loss parity asserted vs sync",
        "pull_delay_sec": PULL_DELAY_SEC,
        "workload": {"app": "mlr", "samples": N, "features": FEATURES,
                     "classes": CLASSES, "epochs": EPOCHS,
                     "batches": BATCHES},
        "arms": arms,
        "quality": {
            "losses_by_arm": {name: [round(v, 6) for v in losses[name]]
                              for name, _, _ in ARMS},
            "note": "per-epoch loss curves, committed as measured: bound "
                    "0 is bit-identical to sync (asserted); bounds 1-2 "
                    "apply updates against a view up to `bound` deltas "
                    "stale — the lag is timing-dependent within [0, "
                    "bound], so those rows are the best-throughput "
                    "round's curve, not a deterministic replay",
        },
        "note": "CPU backend: the overlap win is the injected sleep "
                "moving off the critical path; a real TPU additionally "
                "overlaps device collectives with next-step MXU work",
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ASYNC_STEP_r16.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
