"""Seeded chaos sweep: every scenario class x intensity against the
real control plane, whole-system invariants as the verdict.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/chaos_sweep.py > benchmarks/CHAOS_r18.json

Each cell is one ``(seed, scenario, intensity)`` draw through
harmony_tpu.faults.chaos — the seed contract means any cell replays
byte-identically from its row alone. The committed capture must show
every invariant green at end state; a red cell is a bug (fix it and
pin the schedule in tests/test_chaos.py, as the halog tail-poisoning
and the acked-then-lost submit ack were).

``--quick`` skips the HA takeover scenarios (leader kill + partition),
which dominate wall time — bin/chaos.sh wires the two tiers.
"""
import argparse
import json
import logging
import os
import statistics
import sys
import tempfile
import time

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from harmony_tpu.faults import chaos  # noqa: E402


def _pctl(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return round(xs[idx], 4)


def _dist(xs):
    return {"n": len(xs), "p50": _pctl(xs, 0.50), "p99": _pctl(xs, 0.99),
            "max": _pctl(xs, 1.0),
            "mean": round(statistics.fmean(xs), 4) if xs else None}


#: the sweep grid: (seed, scenario, intensity). Seeds picked once and
#: committed — the capture is replayable row by row. Every scenario
#: class appears; the REQUIRED compositions (partition during takeover,
#: disk fault during commit) appear at two intensities each.
GRID = [
    (11, "halog_enospc", 0.5),
    (12, "halog_enospc", 0.9),
    (3, "halog_torn_write", 0.5),
    (9, "halog_torn_write", 0.9),
    (4, "log_slow_fsync", 0.5),
    (11, "client_partition", 0.5),
    (13, "client_partition", 0.9),
    (3, "lease_disk_flap", 0.5),
    (6, "lease_disk_flap", 0.9),
    (5, "chkp_torn_block", 0.6),
    (8, "chkp_bitrot_read", 0.6),
    (5, "chkp_enospc_commit", 0.6),   # disk fault during commit
    (7, "chkp_enospc_commit", 0.9),
    (11, "repl_partition_heal", 0.5),
    (21, "partition_during_takeover", 0.5),   # the capstone
    (23, "partition_during_takeover", 0.9),
    (22, "overload_storm_leader_kill", 0.5),
]


def run_cell(seed: int, scenario: str, intensity: float) -> dict:
    with tempfile.TemporaryDirectory(prefix="harmony-chaos-") as td:
        report = chaos.run_scenario(seed, intensity=intensity,
                                    scenario=scenario, workdir=td)
    acts = report["acts"]
    cell = {
        "seed": seed,
        "scenario": scenario,
        "intensity": intensity,
        "ok": report["ok"],
        "violations": report["violations"],
        "invariants": {
            f["name"]: ("skipped" if f.get("skipped")
                        else ("ok" if f["ok"] else "VIOLATED"))
            for a in acts
            for f in a.get("invariants", {}).get("findings", [])},
        "fault_fires": {k: v for a in acts
                        for k, v in (a.get("fault_fires") or {}).items()},
        "acked": sum(a.get("acked") or 0 for a in acts),
        "client_errors": sum(a.get("errors") or 0 for a in acts),
        "wall_s": report["wall_s"],
    }
    takeovers = [a["takeover_s"] for a in acts if a.get("takeover_s")]
    if takeovers:
        cell["takeover_s"] = takeovers[0]
    resolves = [a["resolve_s"] for a in acts if a.get("resolve_s")]
    if resolves:
        cell["resolve_s"] = resolves[0]
    return cell


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="skip the HA takeover scenarios (the slow tier)")
    ap.add_argument("--seed-shift", type=int, default=0,
                    help="offset every grid seed (schedule-diversity "
                         "sweeps; the committed capture uses 0)")
    args = ap.parse_args()
    logging.disable(logging.ERROR)  # storms are LOUD by design

    grid = [(s + args.seed_shift, name, i) for s, name, i in GRID
            if not (args.quick and name in chaos.HA_SCENARIOS)]

    doc = {
        "metric": "chaos_sweep",
        "unit": "invariant verdicts / seconds",
        "mode": ("seeded multi-fault schedules (partition + disk + "
                 "crash compositions) against the real control plane; "
                 "whole-system invariants checked at end state; every "
                 "cell replays byte-identically from (seed, scenario, "
                 "intensity)"),
        "config": {
            "scenario_catalog": sorted(chaos.SCENARIOS),
            "invariant_catalog": [
                "exactly_once_epochs", "acked_in_log", "loss_parity",
                "no_orphans", "counter_monotonicity", "chain_integrity",
                "single_leaseholder", "epoch_monotonic",
                "leaseholder_after_heal", "acked_resolved"],
            "job": "mlr 16x4x2, 2 epochs x 1 minibatch (real dispatch)",
            "grid_cells": len(grid),
        },
        "grid": [],
    }
    t_sweep = time.monotonic()
    for seed, name, intensity in grid:
        print(f"# {name} seed={seed} i={intensity} ...", file=sys.stderr)
        t0 = time.monotonic()
        try:
            cell = run_cell(seed, name, intensity)
        except Exception as exc:  # a crashed cell is a red cell
            cell = {"seed": seed, "scenario": name,
                    "intensity": intensity, "ok": False,
                    "violations": ["harness_crash"],
                    "error": repr(exc)}
        cell["cell_wall_s"] = round(time.monotonic() - t0, 1)
        doc["grid"].append(cell)
        print(f"#   ok={cell['ok']} violations={cell['violations']} "
              f"fires={cell.get('fault_fires')} "
              f"wall={cell['cell_wall_s']}s", file=sys.stderr)

    oks = [c for c in doc["grid"] if c["ok"]]
    doc["summary"] = {
        "scenarios_run": len(doc["grid"]),
        "scenarios_ok": len(oks),
        "distinct_scenarios": len({c["scenario"] for c in doc["grid"]}),
        "invariant_violations": sorted(
            {v for c in doc["grid"] for v in c["violations"]}),
        "recovery": {
            "takeover_s": _dist([c["takeover_s"] for c in doc["grid"]
                                 if c.get("takeover_s")]),
            "resolve_s": _dist([c["resolve_s"] for c in doc["grid"]
                                if c.get("resolve_s")]),
        },
        "sweep_wall_s": round(time.monotonic() - t_sweep, 1),
    }
    print(json.dumps(doc, indent=1))
    return 0 if len(oks) == len(doc["grid"]) else 1


if __name__ == "__main__":
    sys.exit(main())
