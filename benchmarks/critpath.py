#!/usr/bin/env python
"""Step-phase budget + critical-path overhead benchmark (PR 13).

The :class:`~harmony_tpu.metrics.phases.PhaseBudgetStore` snapshot runs
on every ledger query and every scrape cycle, and
:func:`~harmony_tpu.metrics.critpath.analyze` on every STATUS — both
inside the jobserver control plane — so their cost is measured, not
assumed. Two stages, swept over the control-plane shapes that matter:

1. **budget** — windowed budget computation (snapshot: per-epoch
   sibling-wall join into ``barrier_wait``, residual closure,
   per-worker fractions), swept over workers 1/4/16;
2. **analyze** — the full critical-path analysis (classification,
   dominant phase, per-epoch gating worker), swept over tenants 2/8.

Prints ONE JSON document; the committed capture is
``benchmarks/CRITPATH_r<N>.json``. Pure CPU/stdlib — comparable across
rounds regardless of accelerator health.

Usage: python benchmarks/critpath.py [--rounds N]
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

#: one epoch's feed shape — a believable budget (compute-dominant with
#: real comm/dispatch/input shares); per-worker skew feeds the barrier
_PHASES = {"input_wait": 0.02, "host_dispatch": 0.01,
           "pull_comm": 0.015, "compute": 0.08, "push_comm": 0.01}
_EPOCHS = 32


def _fill(store, tenants: int, workers: int, epochs: int = _EPOCHS):
    for j in range(tenants):
        for e in range(epochs):
            for w in range(workers):
                store.observe_epoch(
                    f"t{j}", f"t{j}", f"w{w}", e,
                    0.15 + 0.02 * (w % 3),
                    dict(_PHASES))


def bench_budget(rounds: int) -> dict:
    from harmony_tpu.metrics.phases import PhaseBudgetStore

    out = {}
    for workers in (1, 4, 16):
        store = PhaseBudgetStore()
        _fill(store, tenants=8, workers=workers)
        samples = []
        snap = {}
        for _ in range(rounds):
            t0 = time.perf_counter()
            snap = store.snapshot()
            samples.append((time.perf_counter() - t0) * 1000.0)
        out[f"workers_{workers}"] = {
            "snapshot_ms": round(statistics.median(samples), 3),
            "tenants": len(snap),
            "worker_rows": sum(len(r["per_worker"])
                               for r in snap.values()),
        }
    return out


def bench_analyze(rounds: int) -> dict:
    from harmony_tpu.metrics import critpath
    from harmony_tpu.metrics.phases import PhaseBudgetStore

    out = {}
    for tenants in (2, 8):
        store = PhaseBudgetStore()
        _fill(store, tenants=tenants, workers=4)
        snap = store.snapshot()
        samples = []
        verdicts = {}
        for _ in range(rounds):
            t0 = time.perf_counter()
            verdicts = critpath.analyze(snap)
            samples.append((time.perf_counter() - t0) * 1000.0)
        per_epoch = statistics.median(samples) / max(
            sum(len(r["epoch_walls"]) for r in snap.values()), 1)
        out[f"tenants_{tenants}"] = {
            "analyze_ms": round(statistics.median(samples), 3),
            "per_epoch_ms": round(per_epoch, 5),
            "classifications": sorted({
                v["classification"] for v in verdicts.values()}),
        }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="critpath bench")
    ap.add_argument("--rounds", type=int, default=30)
    args = ap.parse_args(argv)
    line = {
        "metric": "step-phase budget computation + critical-path "
                  "analysis overhead",
        "unit": "ms (median)",
        "rounds": args.rounds,
        "epochs_per_tenant": _EPOCHS,
        "budget": bench_budget(args.rounds),
        "analyze": bench_analyze(args.rounds),
    }
    print(json.dumps(line, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
