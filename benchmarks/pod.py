#!/usr/bin/env python
"""Multi-host pod throughput on a virtual 2-process/8-device pod.

Launches the same worker processes the e2e test uses (tests/pod_worker.py:
process 0 = PodJobServer, process 1 = follower in SPMD lockstep over the
global mesh), submits one MLR job over TCP, and records aggregate
samples/sec measured from submit to drain. CPU-mesh numbers — comparable
across rounds, not to a chip.

Prints ONE JSON line. Run: python benchmarks/pod.py
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from common import free_port, sanitized_cpu_env, wait_for_ready  # noqa: E402

EPOCHS = 6
BATCHES = 4
N = 16384  # examples
METRIC = "pod MLR throughput (2-process virtual pod, SPMD lockstep)"


def main() -> None:
    worker = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "pod_worker.py")
    env = sanitized_cpu_env(4)
    coord, pod_port, tcp_port = free_port(), free_port(), free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"127.0.0.1:{coord}", "2", str(pid),
             str(pod_port), str(tcp_port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    try:
        if not wait_for_ready(procs[0], 240):
            print(json.dumps({
                "metric": METRIC, "value": None, "unit": "samples/sec",
                "error": "leader not ready within 240s",
            }))
            return

        from harmony_tpu.config.params import JobConfig, TrainerParams
        from harmony_tpu.jobserver.client import CommandSender

        cfg = JobConfig(
            job_id="pod-bench", app_type="dolphin",
            trainer="harmony_tpu.apps.mlr:MLRTrainer",
            params=TrainerParams(
                num_epochs=EPOCHS, num_mini_batches=BATCHES,
                app_params={"num_classes": 64, "num_features": 1024,
                            "features_per_partition": 128,
                            "step_size": 0.05},
            ),
            num_workers=1,
            user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
                  "data_args": {"n": N, "num_features": 1024,
                                "num_classes": 64}},
        )
        sender = CommandSender(tcp_port)
        t0 = time.perf_counter()
        resp = sender.send_job_submit_command(cfg)  # NOT in an assert:
        if not resp.get("ok"):                      # -O must still submit
            raise RuntimeError(f"submit failed: {resp}")
        timed_out = True
        lead_out = ""
        try:
            while time.perf_counter() - t0 < 1200:
                if not sender.send_status_command().get("running"):
                    timed_out = False
                    break
                time.sleep(0.5)
            wall = time.perf_counter() - t0
            sender.send_shutdown_command()
            lead_out, _ = procs[0].communicate(timeout=120)
            procs[1].communicate(timeout=120)
        except Exception as e:  # dead leader / wedged drain: still one line
            print(json.dumps({
                "metric": METRIC, "value": None, "unit": "samples/sec",
                "wall_sec": round(time.perf_counter() - t0, 1),
                "error": f"{type(e).__name__}: {e}",
            }))
            return
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    out = {"metric": METRIC,
           "unit": "samples/sec", "processes": 2, "global_devices": 8,
           "wall_sec": round(wall, 1)}
    # A drained-but-failed job (or a timeout) must not print an inflated
    # rate: verify the leader's RESULT carries the full loss series.
    result_lines = [ln for ln in lead_out.splitlines()
                    if ln.startswith("RESULT ")]
    losses = []
    if result_lines:
        res = json.loads(result_lines[0][len("RESULT "):])
        job = res.get("local_results", {}).get("pod-bench", {})
        losses = job.get("pod-bench/w0", {}).get("losses", [])
        if "error" in job:
            out.update(value=None, error=f"job failed: {job['error']}")
    if timed_out:
        out.update(value=None, error=f"job still running after {wall:.0f}s")
    elif "error" not in out and len(losses) != EPOCHS:
        out.update(value=None,
                   error=f"expected {EPOCHS} epoch losses, got {losses}")
    elif "error" not in out:
        total = EPOCHS * N
        out.update(value=round(total / wall, 1), examples=total)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
