#!/usr/bin/env python
"""Multi-tenant interference: per-job slowdown under concurrency.

The headline (bench.py) records the AGGREGATE rate of concurrent
MLR+NMF+LDA; this companion records what sharing costs each tenant — the
quantity the reference's global TaskUnit schedule exists to keep fair
(SURVEY.md §2.10: CPU/NET phase interleaving of concurrent jobs on shared
executors). Each job runs once ALONE on the mesh (isolation baseline),
then all three run CONCURRENTLY; per-job slowdown = concurrent wall /
isolated wall (>1 = the tenant got slower), and Jain's index over
per-job slowdowns summarizes fairness (1.0 = perfectly even; 1/n = one
job absorbed all the interference).

With ideal time-slicing of a single device, each of n jobs slows ~n x; a
job slowing far more than its peers means the scheduler is starving it.

Prints ONE JSON line. Runs on whatever backend JAX is pointed at (the
real chip, or the virtual mesh via
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from harmony_tpu.utils.platform import mirror_env_platform_request

mirror_env_platform_request()  # JAX_PLATFORMS=cpu must mean cpu (axon hook)

from bench import enable_compile_cache, job_configs  # noqa: E402
from harmony_tpu.jobserver.server import JobServer  # noqa: E402
from harmony_tpu.parallel.mesh import DevicePool  # noqa: E402
from harmony_tpu.utils.devices import discover_devices  # noqa: E402

EPOCHS = 6  # shorter than the headline: 4 passes of the 3-job set


def _run(devices, configs, timeout_s: float = 1800.0, scheduler=None):
    """Submit ``configs`` together; returns {job_id: wall_seconds} from
    the common start (bench.submit_and_time: done-callback stamping, so a
    fast tenant isn't charged a slow one's completion)."""
    from bench import submit_and_time

    server = JobServer(num_executors=len(devices),
                       device_pool=DevicePool(devices),
                       scheduler=scheduler)
    server.start()
    try:
        return submit_and_time(server, configs, timeout_s)
    finally:
        server.shutdown(timeout=120)


def main() -> None:
    enable_compile_cache()
    try:
        devices = discover_devices()
    except RuntimeError as e:
        print(json.dumps({
            "metric": "multi-tenant fairness (slowdown under concurrency)",
            "value": None, "unit": "jain index over per-job slowdowns",
            "error": f"accelerator unreachable: {e}",
        }))
        return
    scale = 1.0 if devices[0].platform != "cpu" else 0.125
    configs, _ = job_configs(scale, epochs=EPOCHS)

    # warmup: compile every job's programs once so neither phase pays them
    print("warmup (compile) pass:", file=sys.stderr)
    _run(devices, [c for c in configs])

    print("isolation baselines:", file=sys.stderr)
    iso = {}
    for c in configs:
        iso.update(_run(devices, [c]))
        print(f"  {c.job_id}: {iso[c.job_id]:.1f}s alone", file=sys.stderr)

    out = {
        "metric": "multi-tenant fairness (slowdown under concurrency)",
        "unit": "jain index over per-job slowdowns",
        "jobs": len(configs),
        "isolated_wall_s": {j: round(w, 1) for j, w in iso.items()},
        "epochs": EPOCHS,
    }
    # share_all = every job on all executors (the reference's default);
    # carve = disjoint mesh slices per tenant (the BASELINE north-star
    # sharing mode). max_share caps each slice at pool//jobs — WITHOUT it
    # the first arrival's fair share is the whole idle pool and "carve"
    # silently degenerates to FIFO. Needs one executor per job to carve.
    from harmony_tpu.jobserver.scheduler import CarveScheduler

    modes = {"share_all": lambda: "share_all"}
    if len(devices) >= len(configs):
        modes["carve"] = lambda: CarveScheduler(
            max_share=max(1, len(devices) // len(configs)))
    for mode, make_sched in modes.items():
        if mode == "carve":
            # slice-shaped programs differ from the full-mesh shapes the
            # isolation runs compiled — warm them outside the timed run
            print("carve warmup (slice-shape compile) pass:", file=sys.stderr)
            _run(devices, configs, scheduler=make_sched())
        print(f"concurrent run ({mode}):", file=sys.stderr)
        conc = _run(devices, configs, scheduler=make_sched())
        slowdown = {j: conc[j] / iso[j] for j in conc}
        for j, s in slowdown.items():
            print(f"  {j}: {conc[j]:.1f}s concurrent -> slowdown {s:.2f}x",
                  file=sys.stderr)
        vals = list(slowdown.values())
        jain = (sum(vals) ** 2) / (len(vals) * sum(v * v for v in vals))
        out[mode] = {
            "jain": round(jain, 3),
            "slowdown": {j: round(s, 2) for j, s in slowdown.items()},
            "max_slowdown": round(max(vals), 2),
            "concurrent_wall_s": {j: round(w, 1) for j, w in conc.items()},
        }
    out["value"] = out["share_all"]["jain"]
    if "carve" not in out and len(devices) < len(configs):
        out["note"] = (f"carve skipped: {len(devices)} device(s) cannot "
                       f"slice among {len(configs)} jobs")
    elif devices[0].platform == "cpu":
        out["note"] = (
            "cpu-mesh carve numbers are a FLOOR: the in-process-collective "
            "backend serializes multi-device program execution across "
            "slices (parallel/dispatch.py); real TPU slices run "
            "concurrently"
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
