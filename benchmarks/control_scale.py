#!/usr/bin/env python
"""Control-plane churn benchmark (PR 17): a synthetic-tenant fleet
against the REAL jobserver.

Each arm boots a real :class:`JobServer` (real scheduler, real
dispatch, real telemetry loops) behind its TCP command plane and
throws a tenant fleet at it: a submit storm (every tenant submits one
tiny-but-real MLR job through :class:`CommandSender`), a crowd of
STATUS pollers (the dashboard herd), slow-loris connections trickling
partial commands, and dead scrape targets wired into
``HARMONY_OBS_SCRAPE_TARGETS``. The grid is tenants x overload-mode:

- ``overload_on``  — admission control + the degradation ladder
  (jobserver/overload.py) as deployed;
- ``overload_off`` — ``HARMONY_OVERLOAD=0``: same bounded worker
  pool, but no early admission, no ladder, full-fidelity telemetry.

Per cell: submit-to-ack and submit-to-dispatch p50/p99, survival
(tenants whose submission landed inside a bounded per-client retry
budget — the herd member's patience), scrape/diagnose/plan cycle
latency, and the overload monitor's own evidence (ladder transitions,
shed counters). A ``chaos`` act kills the leader mid-storm (HA pair)
and proves every acknowledged submission resolves exactly once on the
successor — acked-then-lost is the one outcome this PR makes
structurally impossible.

Prints ONE JSON document; the committed capture is
``benchmarks/CONTROL_SCALE_r<N>.json``. Pure CPU (tiny MLR jobs on
virtual devices) — comparable across rounds regardless of
accelerator health.

Usage: python benchmarks/control_scale.py [--tenants 32,256,1024]
       [--fleet 192] [--no-chaos]
"""
from __future__ import annotations

import argparse
import json
import os
import queue
import socket
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# -- harness knobs (recorded in the output's config block) ---------------

ARM_ENV = {
    # the production-shaped command plane, with a short deadline so
    # slow-loris eviction churn is visible inside the storm window
    "HARMONY_CMD_WORKERS": "8",
    "HARMONY_CMD_QUEUE": "64",
    "HARMONY_CMD_DEADLINE_MS": "2000",
    # fast telemetry cadence so cycle overruns surface within the storm
    "HARMONY_OBS_SCRAPE_PERIOD": "0.25",
    "HARMONY_OVERLOAD_SUBSET": "8",
    # the storm legitimately queues every tenant's job: the fill/ladder
    # mechanics are under test here, not the production inflight cap
    "HARMONY_OVERLOAD_INFLIGHT": "4096",
    # the fleet's bounded patience: ~15s of jittered wall-clock budget
    # (attempt COUNT must not penalize the arm whose hints pace wider)
    "HARMONY_RETRY_BASE_DELAY": "0.05",
    "HARMONY_RETRY_MAX_ATTEMPTS": "15",
}
DEAD_SCRAPE_TARGETS = 4
LORIS_CONNS = 4          # half the worker pool pinned is pressure;
                         # all of it pinned is a different benchmark
STATUS_POLLERS = 8       # a few dashboards, not a second storm: the
                         # submit herd is the pressure source under test
POLL_PERIOD_S = 0.5      # the dashboard herd's per-client cadence
CLIENT_TIMEOUT_S = 6.0
DISPATCH_DRAIN_S = 60.0


def _tiny_job(job_id: str):
    from harmony_tpu.config.params import JobConfig, TrainerParams

    return JobConfig(
        job_id=job_id, app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=1, num_mini_batches=1,
            app_params={"num_classes": 2, "num_features": 4,
                        "features_per_partition": 2, "step_size": 0.5}),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": 16, "num_features": 4,
                            "num_classes": 2, "seed": 7}},
    )


def _pctl(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return round(xs[idx], 4)


def _dist(xs):
    return {"n": len(xs), "p50": _pctl(xs, 0.50), "p99": _pctl(xs, 0.99),
            "max": _pctl(xs, 1.0),
            "mean": round(statistics.fmean(xs), 4) if xs else None}


def _closed_ports(n):
    """Ports that refuse instantly: bound once, closed before use."""
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    return ports


class _Loris:
    """Persistent slow-loris attackers: connect, trickle a partial
    command, hold until the server evicts, reconnect. They exist to
    pin command workers the way a real half-dead client does."""

    def __init__(self, port: int, conns: int) -> None:
        self.port, self.conns = port, conns
        self.stop = threading.Event()
        self.evictions = 0
        self._threads = [threading.Thread(target=self._run, daemon=True)
                         for _ in range(conns)]

    def _run(self):
        while not self.stop.is_set():
            try:
                s = socket.create_connection(("127.0.0.1", self.port),
                                             timeout=2.0)
                s.sendall(b'{"command": "SLOW')
                s.settimeout(5.0)
                while not self.stop.is_set():
                    if not s.recv(4096):
                        break           # evicted / closed: reconnect
                self.evictions += 1
                s.close()
            except OSError:
                time.sleep(0.05)

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def join(self):
        self.stop.set()
        for t in self._threads:
            t.join(timeout=3.0)


def _build_server(diag_ms, plan_ms, dispatch_ts):
    """A real JobServer with pure-instrumentation wraps: stamp each
    job's scheduler-chosen launch time and time every doctor/policy
    evaluation the telemetry loop makes (the wrapped calls run
    unchanged)."""
    from harmony_tpu.jobserver.server import JobServer

    server = JobServer(num_executors=2)
    orig_launch = server._launch

    def launch(config, executor_ids):
        dispatch_ts[config.job_id] = time.monotonic()
        return orig_launch(config, executor_ids)

    server._launch = launch                 # before start(): bind() sees it
    orig_diag = server.doctor.diagnose

    def diag(now=None, jobs=None):
        t0 = time.monotonic()
        try:
            return orig_diag(now=now, jobs=jobs)
        finally:
            diag_ms.append((time.monotonic() - t0) * 1000.0)

    server.doctor.diagnose = diag
    orig_plan = server.policy.maybe_evaluate

    def plan(jobs=None):
        t0 = time.monotonic()
        try:
            return orig_plan(jobs=jobs)
        finally:
            plan_ms.append((time.monotonic() - t0) * 1000.0)

    server.policy.maybe_evaluate = plan
    return server


def run_arm(tenants: int, overload_on: bool, fleet: int) -> dict:
    from harmony_tpu.faults.retry import RetryError
    from harmony_tpu.jobserver import joblog
    from harmony_tpu.jobserver.client import CommandSender

    saved = {k: os.environ.get(k) for k in
             list(ARM_ENV) + ["HARMONY_OVERLOAD",
                              "HARMONY_OBS_SCRAPE_TARGETS"]}
    os.environ.update(ARM_ENV)
    os.environ["HARMONY_OVERLOAD"] = "1" if overload_on else "0"
    os.environ["HARMONY_OBS_SCRAPE_TARGETS"] = ",".join(
        f"dead{i}=127.0.0.1:{p}"
        for i, p in enumerate(_closed_ports(DEAD_SCRAPE_TARGETS)))
    joblog.clear_events()
    diag_ms, plan_ms, dispatch_ts = [], [], {}
    server = _build_server(diag_ms, plan_ms, dispatch_ts)
    try:
        server.start()
        port = server.serve_tcp()
        # warm the dispatch path so the first tenant doesn't pay the
        # one-time compile inside its measured window
        CommandSender(port).send_job_submit_command(_tiny_job("warmup"))
        server._jobs["warmup"].future.result(timeout=120)

        loris = _Loris(port, LORIS_CONNS).start()
        stop_pollers = threading.Event()

        def poller():
            # the dashboard herd: STATUS is the expensive read command;
            # real dashboards poll at a cadence, they don't spin
            sender = CommandSender(port, timeout=CLIENT_TIMEOUT_S)
            while not stop_pollers.is_set():
                try:
                    sender._roundtrip({"command": "STATUS"})
                except Exception:
                    pass
                stop_pollers.wait(POLL_PERIOD_S)

        pollers = [threading.Thread(target=poller, daemon=True)
                   for _ in range(STATUS_POLLERS)]
        for t in pollers:
            t.start()

        work: "queue.Queue[str]" = queue.Queue()
        for i in range(tenants):
            work.put(f"t{i:04d}")
        acks, outcomes = {}, {"ok": 0, "busy_refused": 0, "error": 0}
        submit_t0 = {}
        lock = threading.Lock()

        def submitter():
            sender = CommandSender(port, timeout=CLIENT_TIMEOUT_S)
            while True:
                try:
                    jid = work.get_nowait()
                except queue.Empty:
                    return
                t0 = time.monotonic()
                with lock:
                    submit_t0[jid] = t0
                try:
                    reply = sender.send_job_submit_command(_tiny_job(jid))
                    ok = bool(reply.get("ok"))
                except RetryError:
                    ok, reply = False, {"busy": True}
                except Exception:
                    ok, reply = False, {}
                with lock:
                    if ok:
                        outcomes["ok"] += 1
                        acks[jid] = time.monotonic() - t0
                    elif reply.get("busy"):
                        outcomes["busy_refused"] += 1
                    else:
                        outcomes["error"] += 1

        t_storm = time.monotonic()
        threads = [threading.Thread(target=submitter, daemon=True)
                   for _ in range(min(fleet, tenants))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=tenants * 2.0 + 120.0)
        storm_s = time.monotonic() - t_storm
        wedged_clients = sum(1 for t in threads if t.is_alive())

        # drain: every ACKED job must reach its scheduler launch —
        # acked-then-lost is the failure this PR forbids
        deadline = time.monotonic() + DISPATCH_DRAIN_S
        while time.monotonic() < deadline:
            with lock:
                missing = [j for j in acks if j not in dispatch_ts]
            if not missing:
                break
            time.sleep(0.1)
        with lock:
            lost = [j for j in acks if j not in dispatch_ts]
            d2d = [dispatch_ts[j] - submit_t0[j]
                   for j in acks if j in dispatch_ts]
        stop_pollers.set()
        loris.join()
        for t in pollers:
            t.join(timeout=3.0)
        if not diag_ms:
            # no scrape cycle completed inside a short storm: drive one
            # representative cycle directly (ledger fully populated)
            server._on_scrape_cycle()
        ov = server.overload.status()
        scraper = server._history_scraper.stats()
        return {
            "tenants": tenants,
            "overload": "on" if overload_on else "off",
            "storm_s": round(storm_s, 2),
            "survival": round(outcomes["ok"] / tenants, 4),
            "outcomes": dict(outcomes),
            "wedged_clients": wedged_clients,
            "acked_jobs_lost": len(lost),
            "submit_to_ack_s": _dist(list(acks.values())),
            "submit_to_dispatch_s": _dist(d2d),
            "diagnose_ms": _dist(diag_ms),
            "plan_ms": _dist(plan_ms),
            "scrape_cycle_ms": scraper.get("last_cycle_ms"),
            "scrape_cycles": scraper.get("cycles"),
            "loris_evictions": loris.evictions,
            "ladder": {
                "level_at_end": ov["level"],
                "transitions": ov["transitions"],
                "sheds": ov["sheds"],
            },
        }
    finally:
        try:
            server.shutdown(timeout=60.0)
        except Exception:
            pass
        joblog.clear_events()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_chaos(tenants: int, tmp_dir: str) -> dict:
    """Leader killed mid-storm: the fleet keeps submitting through a
    failover sender while the leader's command plane goes dark and a
    standby takes the lease. Every acknowledged submission must
    resolve exactly once on the successor."""
    from harmony_tpu.jobserver import joblog
    from harmony_tpu.jobserver.client import CommandSender
    from harmony_tpu.jobserver.ha import HAController
    from harmony_tpu.jobserver.server import JobServer

    saved = {k: os.environ.get(k) for k in ARM_ENV}
    os.environ.update(ARM_ENV)
    os.environ["HARMONY_RETRY_MAX_ATTEMPTS"] = "10"
    os.environ["HARMONY_RETRY_BASE_DELAY"] = "0.1"
    joblog.clear_events()
    try:
        ha_dir = os.path.join(tmp_dir, "ha")
        # A generous lease: on a CPU-saturated bench box a sub-second
        # lease can starve the holder's own renew thread and self-depose
        # the successor mid-resolution, which is lease tuning — not the
        # failover behaviour this phase measures.
        a = HAController(lambda: JobServer(num_executors=2),
                         log_dir=ha_dir, replica_id="rep-a",
                         submit_port=0, lease_s=2.5).start()
        assert a.wait_leader(30)
        a_addr = f"127.0.0.1:{a.port}"
        b_addr = [a_addr]
        acks, errors = {}, [0]
        lock = threading.Lock()

        def submitter(i):
            sender = CommandSender(addrs=[a_addr, b_addr[0]],
                                   timeout=CLIENT_TIMEOUT_S)
            t0 = time.monotonic()
            try:
                r = sender.send_job_submit_command(_tiny_job(f"c{i:03d}"))
            except Exception:
                with lock:
                    errors[0] += 1
                return
            with lock:
                if r.get("ok"):
                    acks[f"c{i:03d}"] = time.monotonic() - t0
                else:
                    errors[0] += 1

        threads = [threading.Thread(target=submitter, args=(i,),
                                    daemon=True) for i in range(tenants)]
        t_kill = None
        for i, t in enumerate(threads):
            t.start()
            if i == tenants // 2:       # mid-storm: the leader dies
                t_kill = time.monotonic()
                a.server._stop_tcp()
                a.lease.stop()
                b = HAController(lambda: JobServer(num_executors=2),
                                 log_dir=ha_dir, replica_id="rep-b",
                                 submit_port=0, lease_s=2.5).start()
                b_addr[0] = f"127.0.0.1:{b.port}"
        assert b.wait_leader(60)
        takeover_s = time.monotonic() - t_kill
        print(f"# chaos: takeover_s={takeover_s:.1f}", file=sys.stderr)
        for t in threads:
            t.join(timeout=180)
        wedged = sum(1 for t in threads if t.is_alive())
        print(f"# chaos: storm joined acked={len(acks)} "
              f"errors={errors[0]} wedged={wedged}", file=sys.stderr)
        failover = CommandSender(addrs=[a_addr, f"127.0.0.1:{b.port}"])
        resolved, unresolved = 0, []

        def _sweep(jids, per_job, budget):
            nonlocal resolved
            timed_out = []
            deadline = time.monotonic() + budget
            for jid in jids:
                left = deadline - time.monotonic()
                if left <= 0:
                    timed_out.append(jid)
                    continue
                try:
                    failover.wait_result(jid, timeout=min(per_job, left))
                except TimeoutError:
                    timed_out.append(jid)
                    continue
                except RuntimeError:
                    pass  # a definitive failure reply IS a resolution
                resolved += 1
            return timed_out

        # two passes: the first visits early ids while the successor is
        # still draining its re-armed backlog, so a timeout there means
        # "not yet", not "lost" — only a job still unresolved on the
        # second pass (after the whole drain had the first pass's wall
        # clock to finish) counts as a lost ack
        retry = _sweep(sorted(acks), per_job=60.0, budget=300.0)
        if retry:
            print(f"# chaos: first pass resolved={resolved}, "
                  f"retrying {len(retry)}", file=sys.stderr)
            unresolved = _sweep(retry, per_job=60.0, budget=120.0)
        print(f"# chaos: resolved={resolved} unresolved={len(unresolved)}",
              file=sys.stderr)
        status = CommandSender(b.port).send_status_command()
        out = {
            "tenants": tenants,
            "acked": len(acks),
            "errors_or_refused": errors[0],
            "wedged_clients": wedged,
            "resolved_on_successor": resolved,
            "acked_jobs_lost": len(acks) - resolved,
            "unresolved": unresolved[:8],
            "takeover_s": round(takeover_s, 2),
            "successor_ladder": status["overload"]["ladder"],
            "successor_epoch": status["ha"]["leader_epoch"],
        }
        # bounded teardown: the measurements above are already in `out`,
        # and a teardown wedged on a drain must not discard them — the
        # daemon thread is reaped with the process either way
        stopper = threading.Thread(
            target=lambda: (b.stop(), a.stop()), daemon=True)
        stopper.start()
        stopper.join(timeout=90)
        if stopper.is_alive():
            print("# chaos: teardown still draining (abandoned)",
                  file=sys.stderr)
        return out
    finally:
        joblog.clear_events()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tenants", default="32,256,1024")
    ap.add_argument("--fleet", type=int, default=192,
                    help="max concurrent submitting clients (the herd "
                         "width; above the TCP backlog on purpose)")
    ap.add_argument("--no-chaos", action="store_true")
    args = ap.parse_args()
    sizes = [int(x) for x in args.tenants.split(",") if x]

    doc = {
        "metric": "control_scale",
        "unit": "seconds / fraction",
        "mode": ("submit storm + STATUS herd + slow-loris + dead scrape "
                 "targets against the real jobserver; overload-on vs "
                 "overload-off arms; chaos = leader kill mid-storm"),
        "config": {
            "env": dict(ARM_ENV),
            "fleet": args.fleet,
            "dead_scrape_targets": DEAD_SCRAPE_TARGETS,
            "loris_conns": LORIS_CONNS,
            "client_timeout_s": CLIENT_TIMEOUT_S,
            "job": "mlr 16x4x2, 1 epoch x 1 minibatch (real dispatch)",
        },
        "grid": [],
    }
    for n in sizes:
        for on in (True, False):
            label = f"{n}/{'on' if on else 'off'}"
            print(f"# arm {label} ...", file=sys.stderr)
            t0 = time.monotonic()
            cell = run_arm(n, overload_on=on, fleet=args.fleet)
            cell["arm_wall_s"] = round(time.monotonic() - t0, 1)
            doc["grid"].append(cell)
            print(f"# arm {label}: survival={cell['survival']} "
                  f"ack_p99={cell['submit_to_ack_s']['p99']} "
                  f"lost={cell['acked_jobs_lost']} "
                  f"wall={cell['arm_wall_s']}s", file=sys.stderr)
    if not args.no_chaos:
        import tempfile

        print("# chaos: leader kill mid-storm ...", file=sys.stderr)
        with tempfile.TemporaryDirectory() as td:
            try:
                doc["chaos"] = run_chaos(128, td)
            except Exception as exc:   # keep the grid; chaos reruns cheaply
                doc["chaos"] = {"error": repr(exc)}
    print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
