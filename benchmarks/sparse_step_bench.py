#!/usr/bin/env python
"""Fused device hot path — fused vs unfused sparse-step throughput.

Two sparse workloads, each measured in both step modes with the
blockmove_bench methodology (interleaved A/B rounds, best-of per arm, an
in-bench loss-parity assertion before any number is reported):

  * ``embsgd`` — the host-driven path's A/B: an embedding-SGD table
    driven through FusedSparseStep (ONE donated-buffer program per batch,
    double-buffered index staging) vs the ModelAccessor round trip it
    replaces (pull -> numpy -> jitted compute -> numpy -> push: three
    dispatches and two full host crossings per batch);
  * ``lda_worker`` — the trainer-level knob: a WorkerTasklet LDA job
    (topic-word count table — the canonical sparse-table workload) with
    ``TrainerParams.fused_step`` on vs off (the off arm additionally
    reports its MEASURED per-phase pull/comp/push seconds — the unfused
    path times phases directly instead of probing). LDA's count-valued
    state is addition-order-insensitive, so the bit-identical gate holds
    at any scale (MLR's gradient matmuls drift in the last float bit
    between program builds — see docs/DEVICE_HOT_PATH.md).

Honesty note: this host's ~2-core CPU quota sets a thread-scaling ceiling
(~1.4x measured in BLOCKMOVE_r06) and the CPU backend executes one
program at a time, so the fused win here is dispatch/host-crossing
elimination only — on a real TPU the donated-buffer chain additionally
keeps the table in HBM across batches and the Pallas gather/scatter
kernels (ops/sparse.py) replace the XLA scatter serialization, which this
bench cannot see.

Prints ONE JSON line. Run: python benchmarks/sparse_step_bench.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

ROUNDS = 4

# embsgd shape: wide-ish rows, small batch compute — the regime where the
# host round trip (2 transfers + 3 dispatches per batch) dominates.
ROWS, WIDTH, BATCH, NBATCH = 4096, 64, 256, 60

# lda_worker shape
LDA_DOCS, LDA_VOCAB, LDA_TOPICS, LDA_LEN, LDA_EPOCHS, LDA_BATCHES = (
    1024, 2000, 16, 32, 4, 8)


def _mesh():
    from harmony_tpu.parallel import build_mesh

    return build_mesh(jax.devices("cpu")[:1])


def _emb_table(mesh):
    from harmony_tpu.config.params import TableConfig
    from harmony_tpu.table import DenseTable, TableSpec

    return DenseTable(
        TableSpec(TableConfig(table_id="emb-bench", capacity=ROWS,
                              value_shape=(WIDTH,), num_blocks=64)),
        mesh,
    )


def _emb_batches(seed=0):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, ROWS, BATCH).astype(np.int32),
         rng.normal(size=(BATCH, WIDTH)).astype(np.float32))
        for _ in range(NBATCH)
    ]


def _sgd_compute(rows, targets):
    err = rows - targets
    loss = jnp.mean(jnp.sum(err * err, -1))
    return -0.05 * err, {"loss": loss}


def run_embsgd(fused: bool):
    """One pass; returns (samples_per_sec, losses, phase_seconds)."""
    mesh = _mesh()
    table = _emb_table(mesh)
    batches = _emb_batches()
    from harmony_tpu.dolphin import ModelAccessor

    acc = ModelAccessor(table)
    if fused:
        fs = acc.fused_step(_sgd_compute, signature=("embsgd-bench",))
        fs.run_batches(batches[:2])  # warmup: compile
        t0 = time.perf_counter()
        auxes = fs.run_batches(batches)
        dt = time.perf_counter() - t0
        losses = [float(a["loss"]) for a in auxes]
        phases = {"comp_s": round(fs.comp_tracer.total_sec, 4)}
    else:
        comp = jax.jit(_sgd_compute)
        comp_t = 0.0

        def one(keys, tgt):
            nonlocal comp_t
            rows = acc.pull(keys)                       # PULL (D2H)
            t0 = time.perf_counter()
            delta, aux = jax.block_until_ready(
                comp(jnp.asarray(rows), jnp.asarray(tgt)))  # COMP
            comp_t += time.perf_counter() - t0
            acc.push(keys, np.asarray(delta))           # PUSH (H2D scatter)
            return float(aux["loss"])

        for keys, tgt in batches[:2]:  # warmup: compile all three programs
            one(keys, tgt)
        acc.get_and_reset_times()
        comp_t = 0.0
        t0 = time.perf_counter()
        losses = [one(keys, tgt) for keys, tgt in batches]
        dt = time.perf_counter() - t0
        pull_s, push_s = acc.get_and_reset_times()
        phases = {"pull_s": round(pull_s, 4), "comp_s": round(comp_t, 4),
                  "push_s": round(push_s, 4)}
    # warmup touched the table: both arms warmed on the SAME two batches
    # from the same init, so the measured-run losses stay comparable
    return len(batches) * BATCH / dt, losses, phases


def run_lda_worker(fused: bool):
    from harmony_tpu.apps.lda import LDATrainer, make_synthetic
    from harmony_tpu.config.params import TrainerParams
    from harmony_tpu.dolphin import (
        TrainerContext,
        TrainingDataProvider,
        WorkerTasklet,
    )
    from harmony_tpu.table import DenseTable, TableSpec

    mesh = _mesh()
    trainer = LDATrainer(vocab_size=LDA_VOCAB, num_topics=LDA_TOPICS,
                         num_docs=LDA_DOCS, max_doc_len=LDA_LEN)
    table = DenseTable(TableSpec(trainer.model_table_config()), mesh)
    ltable = DenseTable(TableSpec(trainer.local_table_config()), mesh)
    params = TrainerParams(num_epochs=LDA_EPOCHS,
                           num_mini_batches=LDA_BATCHES, fused_step=fused)
    ctx = TrainerContext(params=params, model_table=table,
                         local_table=ltable)
    data = TrainingDataProvider(
        make_synthetic(LDA_DOCS, LDA_VOCAB, LDA_TOPICS, LDA_LEN, seed=7),
        LDA_BATCHES)
    w = WorkerTasklet("lda-bench", ctx, trainer, data, mesh)
    t0 = time.perf_counter()
    result = w.run()
    dt = time.perf_counter() - t0
    phases = {}
    split = getattr(w._step, "mean_phase_seconds", None)
    if split is not None:
        p, c, q = split()
        phases = {"pull_s": round(p, 5), "comp_s": round(c, 5),
                  "push_s": round(q, 5)}
    return LDA_DOCS * LDA_EPOCHS / dt, result["losses"], phases


def main() -> None:
    workloads = {}
    for name, runner in (("embsgd", run_embsgd),
                         ("lda_worker", run_lda_worker)):
        best = {True: 0.0, False: 0.0}
        phases = {True: {}, False: {}}
        ref_losses = {}
        for _ in range(ROUNDS):
            # interleaved arms inside every round (host throughput drifts
            # round to round), best-of per arm
            for fused in (True, False):
                sps, losses, ph = runner(fused)
                if fused in ref_losses:
                    assert losses == ref_losses[fused], (
                        f"{name}: nondeterministic losses within one arm")
                ref_losses[fused] = losses
                if sps > best[fused]:
                    best[fused] = sps
                    phases[fused] = ph
        # the parity gate: a fused number only counts if the fused arm
        # learns EXACTLY what the unfused arm learns (bit-identical)
        assert ref_losses[True] == ref_losses[False], (
            f"{name}: fused/unfused loss parity broke: "
            f"{ref_losses[True][:3]} vs {ref_losses[False][:3]}")
        workloads[name] = {
            "fused_sps": round(best[True], 1),
            "unfused_sps": round(best[False], 1),
            "speedup": round(best[True] / best[False], 2),
            "loss_parity": "bit-identical",
            "phases_fused": phases[True],
            "phases_unfused": phases[False],
        }
    print(json.dumps({
        "metric": "sparse_step",
        "unit": "samples/sec",
        "rounds": ROUNDS,
        "mode": "interleaved A/B, best-of per arm, in-bench bit-identical "
                "loss parity asserted per workload",
        "workloads": workloads,
        "note": "CPU backend, ~2-core host quota: the fused win here is "
                "host-crossing/dispatch elimination only; TPU adds "
                "donated-buffer HBM residency + Pallas gather/scatter "
                "kernels this bench cannot measure",
    }))


if __name__ == "__main__":
    main()
