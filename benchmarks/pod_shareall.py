#!/usr/bin/env python
"""Share-all aggregate throughput A/B (round-4 verdict item 5).

POD_TENANTS measures per-tenant slowdown and fairness; this artifact
measures the thing share-all EXISTS for: aggregate throughput above
serialized admission. Two heterogeneous tenants on a 2-process virtual
pod — a STALLING job (LaggyMLRTrainer: host-side stalls each epoch, the
data-wait/preprocessing analog) and a COMPUTE job (larger MLR model) —
run A/B:

  * share_all — both submitted at once under the unit protocol; the
    compute tenant's dispatches fill the staller's stall gaps;
  * serialized — identical configs with user.pod_isolated, so admission
    runs them one at a time (the pre-round-4 behavior for multi-process
    tenants).

Aggregate = total samples / wall(first submit -> drain). Medians over
REPEATS runs per arm (1-core host noise; same-session A/B only — walls
are not comparable across sessions). Writes
benchmarks/POD_SHAREALL_<suffix>.json and prints one JSON line.

Run: python benchmarks/pod_shareall.py [suffix]   (default r05)
NOTE: pause bin/watch_chip.sh first — its jax-importing probes spike
1-core CPU walls (ROUNDLOG round-3 note).
"""
import json
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import free_port, sanitized_cpu_env, wait_for_ready  # noqa: E402

REPEATS = 3
EPOCHS = 12          # amortize first-compile; stalls dominate the staller
BATCHES = 2
N_STALL = 512        # staller: small data, real stalls
N_COMPUTE = 4096     # compute tenant: device-heavy steps
LAG_SEC = 0.6        # per-epoch host stall of the stalling tenant


def _cfgs(isolated: bool):
    from harmony_tpu.config.params import JobConfig, TrainerParams

    stall = JobConfig(
        job_id="ab-stall", app_type="dolphin",
        trainer="tests.helpers:LaggyMLRTrainer",
        params=TrainerParams(
            num_epochs=EPOCHS, num_mini_batches=BATCHES, clock_slack=1,
            app_params={"lag_sec": LAG_SEC, "lag_worker": "/w0",
                        "num_classes": 8, "num_features": 64,
                        "features_per_partition": 16, "step_size": 0.1},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": N_STALL, "num_features": 64,
                            "num_classes": 8, "seed": 31}},
    )
    compute = JobConfig(
        job_id="ab-compute", app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=EPOCHS, num_mini_batches=BATCHES,
            app_params={"num_classes": 64, "num_features": 1024,
                        "features_per_partition": 256,
                        "step_size": 0.05},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": N_COMPUTE, "num_features": 1024,
                            "num_classes": 64, "seed": 32}},
    )
    if isolated:
        for cfg in (stall, compute):
            cfg.user["pod_isolated"] = True
    return [stall, compute]


def run_arm(isolated: bool) -> dict:
    """One pod run; returns aggregate samples/sec + per-job walls."""
    from harmony_tpu.jobserver.client import CommandSender

    worker = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "pod_worker.py")
    env = sanitized_cpu_env(2)
    coord, pod_port, tcp_port = free_port(), free_port(), free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"127.0.0.1:{coord}", "2", str(pid),
             str(pod_port), str(tcp_port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    try:
        if not wait_for_ready(procs[0], 240):
            raise RuntimeError("pod leader not ready")
        sender = CommandSender(tcp_port)
        cfgs = _cfgs(isolated)
        t0 = time.perf_counter()
        for cfg in cfgs:
            resp = sender.send_job_submit_command(cfg)
            if not resp.get("ok"):
                raise RuntimeError(f"submit failed: {resp}")
            time.sleep(0.2)  # deterministic isolated-arm ticket order
        deadline = time.perf_counter() + 900
        while time.perf_counter() < deadline:
            if not sender.send_status_command().get("running"):
                break
            time.sleep(0.2)
        else:
            raise RuntimeError("pod never drained")
        wall = time.perf_counter() - t0
        sender.send_shutdown_command()
        outs = [p.communicate(timeout=120)[0] for p in procs]
        lead = [ln for ln in outs[0].splitlines()
                if ln.startswith("RESULT ")]
        walls = {}
        if lead:
            jw = json.loads(lead[0][len("RESULT "):]).get("job_walls", {})
            walls = {j: [round(w[0] - t0, 2), round(w[1] - t0, 2)]
                     for j, w in jw.items()}
        samples = EPOCHS * (N_STALL + N_COMPUTE)
        return {"rate": samples / wall, "wall_s": round(wall, 2),
                "job_walls": walls}
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main() -> None:
    suffix = sys.argv[1] if len(sys.argv) > 1 else "r05"
    # interleave arms so slow host drift hits both equally
    share, serial = [], []
    for _ in range(REPEATS):
        share.append(run_arm(isolated=False))
        serial.append(run_arm(isolated=True))
    med_share = statistics.median(r["rate"] for r in share)
    med_serial = statistics.median(r["rate"] for r in serial)
    out = {
        "metric": "pod share-all aggregate throughput vs serialized",
        "unit": "samples/sec",
        "tenants": {
            "ab-stall": {"lag_sec_per_epoch": LAG_SEC, "n": N_STALL,
                         "epochs": EPOCHS},
            "ab-compute": {"n": N_COMPUTE, "features": 1024,
                           "classes": 64, "epochs": EPOCHS},
        },
        "share_all_runs": share,
        "serialized_runs": serial,
        "share_all_median": round(med_share, 1),
        "serialized_median": round(med_serial, 1),
        "speedup": round(med_share / med_serial, 3),
        "note": ("same-session A/B, interleaved runs, medians of "
                 f"{REPEATS}. 1-core host: the compute tenant fills the "
                 "staller's stall gaps (job_walls show it running fully "
                 "INSIDE the staller's window under share_all), but "
                 "every saved stall-second is partly repaid in core "
                 "timesharing — the SIGN of the comparison transfers, "
                 "magnitudes do not. On real chips the tenants' device "
                 "work does not timeshare a single host core, so the "
                 "overlap gain is strictly larger."),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"POD_SHAREALL_{suffix}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "metric": out["metric"],
        "share_all": out["share_all_median"],
        "serialized": out["serialized_median"],
        "speedup": out["speedup"],
        "artifact": path,
    }))


if __name__ == "__main__":
    main()
