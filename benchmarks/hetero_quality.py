#!/usr/bin/env python
"""Heterogeneous-solver plan quality beyond the exact-enumeration limit.

The ILPSolver enumerates owner sets exhaustively up to
``exact_enum_limit`` executors and switches to greedy-seed + swap local
search above (the Gurobi replacement's scale path — round-2 verdict:
"beyond-12 plan quality is unmeasured"). This artifact measures it: for
random heterogeneous profiles at several pool sizes, the heuristic's
predicted mini-batch time is compared against the TRUE optimum from full
enumeration (feasible offline up to n=16: 65k owner sets of cheap host
math). Reported per size: worst and mean quality ratio
(heuristic / exact; 1.0 = optimal) over trials, plus the seed-only ratio
showing what the local search buys.

Pure host math — no devices. Writes benchmarks/HETERO_QUALITY_r03.json;
prints ONE JSON line. Run: python benchmarks/hetero_quality.py
"""
import itertools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from harmony_tpu.optimizer.hetero import ExecutorProfile, ILPSolver  # noqa: E402

SIZES = (12, 14, 16)
TRIALS = 20
DATA_BLOCKS, MODEL_BLOCKS, COMM = 256, 64, 0.004
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "HETERO_QUALITY_r03.json")


def _profiles(rng, n):
    return [
        ExecutorProfile(
            executor_id=f"e{i}",
            rate=float(rng.uniform(0.5, 4.0)),
            bandwidth=float(rng.uniform(0.2, 8.0)),
        )
        for i in range(n)
    ]


def _exact(solver, profiles):
    best = None
    n = len(profiles)
    for k in range(1, n):
        for owner_ids in itertools.combinations(range(n), k):
            a = solver._eval_owner_set(
                owner_ids, profiles, DATA_BLOCKS, MODEL_BLOCKS, COMM
            )
            if a and (best is None or a.predicted_time < best.predicted_time):
                best = a
    return best


def main() -> None:
    rng = np.random.default_rng(7)
    heuristic = ILPSolver(exact_enum_limit=2)   # force the scale path
    exact_solver = ILPSolver(exact_enum_limit=64)
    rows = []
    for n in SIZES:
        ratios, seed_ratios = [], []
        for _ in range(TRIALS):
            profiles = _profiles(rng, n)
            opt = _exact(exact_solver, profiles).predicted_time
            heur = heuristic.solve(
                profiles, DATA_BLOCKS, MODEL_BLOCKS, COMM
            ).predicted_time
            # seed-only baseline: the solver's OWN seed sets, no search
            seed = None
            for owner_ids in ILPSolver.seed_sweep_sets(profiles):
                a = heuristic._eval_owner_set(
                    owner_ids, profiles, DATA_BLOCKS, MODEL_BLOCKS, COMM)
                if a and (seed is None
                          or a.predicted_time < seed.predicted_time):
                    seed = a
            ratios.append(heur / opt)
            seed_ratios.append(seed.predicted_time / opt)
        rows.append({
            "n": n, "trials": TRIALS,
            "quality_mean": round(float(np.mean(ratios)), 4),
            "quality_worst": round(float(np.max(ratios)), 4),
            "seed_only_mean": round(float(np.mean(seed_ratios)), 4),
            "seed_only_worst": round(float(np.max(seed_ratios)), 4),
        })
    out = {
        "metric": "hetero solver plan quality beyond exact limit",
        "unit": "heuristic/exact predicted time (1.0 = optimal)",
        "value": max(r["quality_worst"] for r in rows),
        "sizes": rows,
        "note": ("exact = full owner-set enumeration (the offline optimum); "
                 "heuristic = greedy seed + swap local search, the path "
                 "used for pools above exact_enum_limit"),
    }
    with open(OUT, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
