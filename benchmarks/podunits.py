#!/usr/bin/env python
"""Unit-protocol cost under injected DCN latency (round-4 verdict item 4).

The cross-job unit protocol pays one control-plane round trip per unit —
the same bill the reference's per-TaskUnit wait/ready message pair pays
(GlobalTaskUnitScheduler.java:64-85). On localhost pods that RTT is
microseconds; this bench prices it at REAL DCN RTTs by sweeping the
HARMONY_POD_UNIT_LAT_MS injection knob (runtime/podunits.py) at one-way
0 / 0.5 / 2.5 ms == RTT 0 / 1 / 5 ms, two ways:

  * MICRO — the protocol alone over real sockets: a leader arbiter and
    two follower processes' worth of FollowerUnits wired over socketpairs
    with the pod's JSON-line framing, two CONTENDED jobs cycling units
    (overlapping process sets => units fully serialize, the worst case).
    Reports per-serialized-unit acquisition cost at each RTT.
  * E2E — a real 2-process virtual pod with two overlapping share-all MLR
    tenants at RTT 0 and 5 ms: wall time, the leader's units_granted
    counter, and the implied overhead/unit (noisy on a 1-core host; the
    micro numbers are the load-bearing ones).

From those it JUSTIFIES the default unit coarseness: uncontended jobs
fuse multi-epoch dispatch windows into ONE unit (epoch-window default:
up to 4 epochs/unit); the contended flag shrinks windows to one epoch
per unit so tenants interleave at epoch granularity. The artifact
records overhead-per-unit next to the measured per-epoch compute time,
i.e. the fraction of an epoch the protocol costs at each RTT.

Writes benchmarks/PODUNITS_<suffix>.json and prints one JSON line.
Run: python benchmarks/podunits.py [suffix]   (default r06)
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import free_port, sanitized_cpu_env, wait_for_ready  # noqa: E402

ONE_WAY_MS = [0.0, 0.5, 2.5]  # RTT 0 / 1 / 5 ms
MICRO_UNITS = 300             # units per job per sweep point
E2E_EPOCHS = 4


# -- micro: the protocol over real sockets -------------------------------


def _serve_follower(arbiter, pid, conn):
    """Leader-side reader for one follower socket (the pod reader loop's
    TU_* subset)."""
    f = conn.makefile("r")
    for line in f:
        msg = json.loads(line)
        if msg["cmd"] == "TU_WAIT":
            arbiter.on_wait(msg["job_id"], msg["seq"], pid,
                            retry=bool(msg.get("retry", False)))
        elif msg["cmd"] == "TU_DONE":
            arbiter.on_done(msg["job_id"], msg["seq"], pid)
        elif msg["cmd"] == "BYE":
            return


def _follower_loop(units, conn):
    """Follower-side reader: feed TU_GRANTs into FollowerUnits."""
    f = conn.makefile("r")
    for line in f:
        msg = json.loads(line)
        if msg["cmd"] == "TU_GRANT":
            units.on_grant(msg["job_id"], msg["seq"], msg["contended"])
        elif msg["cmd"] == "BYE":
            return


def micro_point(one_way_ms: float, n_followers: int = 2) -> dict:
    """``n_followers`` follower pids, two jobs BOTH spanning all of them
    (fully contended: their units serialize pod-wide; every unit needs a
    grant broadcast to N pids and N DONEs back), MICRO_UNITS units per
    job; returns per-serialized-unit wall cost at the injected latency."""
    from harmony_tpu.runtime.podunits import (
        FollowerUnits, PodUnitArbiter, follower_client,
    )

    pids = list(range(1, n_followers + 1))
    os.environ["HARMONY_POD_UNIT_LAT_MS"] = str(one_way_ms)
    try:
        # leader<->follower socketpairs with the pod's JSON-line framing
        pairs = {pid: socket.socketpair() for pid in pids}
        wfiles = {pid: pairs[pid][0].makefile("w") for pid in pids}
        send_lock = threading.Lock()

        def send_to(pid, msg):
            with send_lock:
                wfiles[pid].write(json.dumps(msg) + "\n")
                wfiles[pid].flush()

        arbiter = PodUnitArbiter(send_to=send_to)
        followers = {}
        threads = []
        for pid in pids:
            fw = pairs[pid][1].makefile("w")
            flock = threading.Lock()

            def report(msg, _fw=fw, _l=flock):
                with _l:
                    _fw.write(json.dumps(msg) + "\n")
                    _fw.flush()

            units = FollowerUnits(report=report)
            followers[pid] = units
            threads.append(threading.Thread(
                target=_serve_follower, args=(arbiter, pid, pairs[pid][0]),
                daemon=True))
            threads.append(threading.Thread(
                target=_follower_loop, args=(units, pairs[pid][1]),
                daemon=True))
        for t in threads:
            t.start()
        for job in ("A", "B"):
            arbiter.register_job(job, frozenset(pids))

        def run_job(pid, job):
            client = follower_client(followers[pid], job)
            for _ in range(MICRO_UNITS):
                with client.scope(timeout=120):
                    pass

        t0 = time.perf_counter()
        workers = [threading.Thread(target=run_job, args=(pid, job))
                   for pid in pids for job in ("A", "B")]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        wall = time.perf_counter() - t0
        serialized_units = 2 * MICRO_UNITS  # A and B never overlap units
        return {
            "one_way_ms": one_way_ms,
            "rtt_ms": 2 * one_way_ms,
            "followers": n_followers,
            "units": serialized_units,
            "wall_s": round(wall, 4),
            "per_unit_ms": round(wall / serialized_units * 1000, 4),
            "grants": arbiter.grants_total,
        }
    finally:
        os.environ.pop("HARMONY_POD_UNIT_LAT_MS", None)
        for a, b in pairs.values():
            for s in (a, b):
                try:
                    s.close()
                except OSError:
                    pass


# -- e2e: a real virtual pod under latency -------------------------------


def _mlr_cfg(job_id, seed):
    from harmony_tpu.config.params import JobConfig, TrainerParams

    return JobConfig(
        job_id=job_id, app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=E2E_EPOCHS, num_mini_batches=4,
            app_params={"num_classes": 16, "num_features": 256,
                        "features_per_partition": 64, "step_size": 0.05},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": 2048, "num_features": 256,
                            "num_classes": 16, "seed": seed}},
    )


def e2e_point(one_way_ms: float) -> dict:
    from harmony_tpu.jobserver.client import CommandSender

    worker = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "pod_worker.py")
    env = sanitized_cpu_env(2)
    if one_way_ms:
        env["HARMONY_POD_UNIT_LAT_MS"] = str(one_way_ms)
    coord, pod_port, tcp_port = free_port(), free_port(), free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"127.0.0.1:{coord}", "2", str(pid),
             str(pod_port), str(tcp_port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    try:
        if not wait_for_ready(procs[0], 240):
            raise RuntimeError("pod leader not ready")
        sender = CommandSender(tcp_port)
        t0 = time.perf_counter()
        for seed, jid in ((21, "lat-a"), (22, "lat-b")):
            resp = sender.send_job_submit_command(_mlr_cfg(jid, seed))
            if not resp.get("ok"):
                raise RuntimeError(f"submit failed: {resp}")
        units = 0
        deadline = time.perf_counter() + 600
        while time.perf_counter() < deadline:
            status = sender.send_status_command()
            units = status.get("pod", {}).get("units_granted", units)
            if not status.get("running"):
                break
            time.sleep(0.2)
        else:
            raise RuntimeError("e2e pod never drained")
        wall = time.perf_counter() - t0
        sender.send_shutdown_command()
        for p in procs:
            p.communicate(timeout=120)
        return {
            "one_way_ms": one_way_ms,
            "rtt_ms": 2 * one_way_ms,
            "wall_s": round(wall, 3),
            "units_granted": units,
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main() -> None:
    suffix = sys.argv[1] if len(sys.argv) > 1 else "r06"
    micro = [micro_point(ms) for ms in ONE_WAY_MS]
    base = micro[0]["per_unit_ms"]
    for row in micro:
        row["overhead_vs_rtt0_ms"] = round(row["per_unit_ms"] - base, 4)
    # follower-count scaling at the worst RTT: a unit's critical path is
    # one grant leg + the slowest DONE leg, so per-unit cost should stay
    # ~flat as followers widen (the legs are concurrent, the arbiter's
    # work is O(followers) socket writes). 8 followers x 1 host process
    # each == the v5p-32 target shape (round-5 verdict): the control
    # plane must price flat out to the real deployment width.
    scale = [micro_point(ONE_WAY_MS[-1], n) for n in (2, 4, 6, 8)]
    e2e = [e2e_point(ms) for ms in (0.0, 2.5)]
    d_wall = e2e[1]["wall_s"] - e2e[0]["wall_s"]
    units5 = max(e2e[1]["units_granted"], 1)
    protocol_cost_s = units5 * micro[-1]["per_unit_ms"] / 1000
    epochs_total = 2 * E2E_EPOCHS
    epoch_ms = e2e[0]["wall_s"] / epochs_total * 1000
    v5p32 = scale[-1]  # the 8x1 row (8 followers, coarse units, 1-core host)
    out = {
        "metric": "pod unit-protocol overhead under injected DCN RTT",
        "micro": micro,
        "follower_scaling_at_rtt5": scale,
        "v5p32_shape_8x1": dict(
            v5p32,
            note=(
                "v5p-32 control-plane shape: 8 followers, fully-contended "
                "pair of jobs at RTT 5 ms. On this 1-CORE host the 8x1 "
                "row runs 32 protocol threads, so per_unit_ms growth vs "
                "the 2-follower row tracks host thread contention, not "
                "protocol cost (the arbiter's work is O(followers) socket "
                "writes; grant and DONE legs are concurrent). The "
                "load-bearing claims at 8x1 are the protocol invariants "
                "(tests/test_podunits.py 8-follower storm) and that every "
                "unit still grants exactly once (grants == units)."
            ),
        ),
        "e2e": e2e,
        "e2e_wall_delta_s": round(d_wall, 3),
        "e2e_predicted_protocol_cost_s": round(protocol_cost_s, 3),
        "e2e_note": (
            "the predicted protocol cost at RTT 5 ms "
            f"({units5} units x {micro[-1]['per_unit_ms']:.2f} ms = "
            f"{protocol_cost_s:.2f}s) is smaller than 1-core host wall "
            "noise, so the e2e delta sits inside noise — the default "
            "coarseness amortizes real DCN RTTs to invisibility; micro "
            "rows carry the per-unit price"),
        "coarseness_defaults": {
            "uncontended": "multi-epoch dispatch window fused into ONE "
                           "unit (up to 4 epochs/unit)",
            "contended": "window shrinks to 1 epoch/unit so tenants "
                         "interleave at epoch granularity",
            "justification": (
                f"at RTT 5 ms the protocol costs "
                f"{micro[-1]['per_unit_ms']:.2f} ms per serialized unit "
                f"(micro); one CPU-bench epoch costs ~{epoch_ms:.0f} ms, "
                f"so even the finest default unit (1 epoch) keeps "
                f"protocol overhead at "
                f"{micro[-1]['per_unit_ms'] / epoch_ms * 100:.1f}% — and "
                f"real steps on a chip are larger. Sub-epoch units would "
                f"multiply the RTT bill for no interleaving gain beyond "
                f"the SSP slack already provided."
            ),
        },
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"PODUNITS_{suffix}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "metric": out["metric"],
        "per_unit_ms_at_rtt": {
            str(r["rtt_ms"]): r["per_unit_ms"] for r in micro},
        "artifact": path,
    }))


if __name__ == "__main__":
    main()
