#!/usr/bin/env python
"""Where does the headline bench's wall-clock actually go?

bench.py's first real-chip capture (BENCH_r02_chip.json) recorded the
accelerator aggregate BELOW the CPU baseline (0.75x) — on a remote-attached
chip the per-sample compute is trivial, so the wall must be going to
host<->device overheads the virtual-mesh runs never see. This harness
separates them:

  primitives   dispatch round-trip, D2H scalar read, H2D bandwidth, and
               compile-cache behavior (fresh-closure re-jit + subprocess
               persistent-cache hit) — the per-op budget everything else
               is made of.
  phases       one MLR job (the bench's config) run under the JobServer
               with the in-memory span receiver installed; prints total
               time per span type (epoch / comm_probe / metric_drain /
               dataset_upload) so the overhead shows up named.

Run on the real chip (plain) or CPU (JAX_PLATFORMS=cpu). Prints one JSON
line per section, like the other bench files.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from harmony_tpu.utils.platform import mirror_env_platform_request

mirror_env_platform_request()  # JAX_PLATFORMS=cpu must mean cpu (axon hook)

import jax.numpy as jnp
import numpy as np


def _t(fn, repeats=10, warmup=1):
    for _ in range(warmup):
        fn()
    best, total = float("inf"), 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = min(best, dt)
        total += dt
    return best, total / repeats


def bench_primitives() -> dict:
    from harmony_tpu.utils.platform import hard_sync

    dev = jax.devices()[0]
    one = jax.device_put(jnp.float32(1.0), dev)
    add = jax.jit(lambda x: x + 1.0)
    float(add(one))
    # dispatch_only: enqueue + (possibly fake) block — the per-op host
    # overhead. rtt: dispatch + VALUE read — the true round trip; on a
    # lazy backend (axon) only the latter includes execution.
    disp_best, _ = _t(lambda: jax.block_until_ready(add(one)))
    rtt_best, rtt_mean = _t(lambda: float(add(one)))

    arr = jax.device_put(jnp.zeros((256, 256), jnp.float32), dev)
    d2h_best, d2h_mean = _t(lambda: np.asarray(arr))

    big = np.zeros((64, 1024, 1024), np.float32)  # 256 MB
    h2d_best, _ = _t(
        lambda: hard_sync(jax.device_put(big, dev)),
        repeats=3, warmup=1,
    )
    h2d_gbps = big.nbytes / h2d_best / 1e9

    # compile-cache behavior: same jaxpr, fresh closure each time — the jit
    # in-memory cache cannot hit, so this measures trace + (persistent-cache
    # hit or full compile). The headline bench rebuilds its jitted steps per
    # JobServer run, so THIS is the cost its measured pass pays per program.
    x = jax.device_put(jnp.ones((1024, 1024), jnp.bfloat16), dev)

    def fresh():
        f = jax.jit(lambda a: (a @ a).sum())
        hard_sync(f(x))

    t0 = time.perf_counter()
    fresh()
    first_compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    fresh()
    refresh_compile_s = time.perf_counter() - t0

    return {
        "metric": "headline primitives",
        "device": str(dev),
        "dispatch_only_ms": round(disp_best * 1e3, 2),
        "dispatch_rtt_ms": round(rtt_best * 1e3, 2),
        "dispatch_rtt_mean_ms": round(rtt_mean * 1e3, 2),
        "d2h_small_ms": round(d2h_best * 1e3, 2),
        "d2h_small_mean_ms": round(d2h_mean * 1e3, 2),
        "h2d_gbps": round(h2d_gbps, 2),
        "fresh_jit_first_s": round(first_compile_s, 2),
        "fresh_jit_again_s": round(refresh_compile_s, 2),
        "value": round(rtt_best * 1e3, 2),
        "unit": "ms dispatch RTT",
    }


def bench_phases(epochs: int = 3) -> dict:
    from bench import job_configs  # repo root on sys.path via parent insert
    from harmony_tpu.jobserver.server import JobServer
    from harmony_tpu.parallel.mesh import DevicePool
    from harmony_tpu.tracing import InMemorySpanReceiver, get_tracing

    recv = get_tracing().add_receiver(InMemorySpanReceiver())
    configs, totals = job_configs(scale=1.0, epochs=epochs)
    mlr = configs[0]
    devices = jax.devices()[:1]
    server = JobServer(num_executors=1, device_pool=DevicePool(devices))
    server.start()
    try:
        t0 = time.perf_counter()
        server.submit(mlr).result(timeout=1800)
        wall = time.perf_counter() - t0
    finally:
        server.shutdown(timeout=60)
        get_tracing().remove_receiver(recv)
    agg: dict = {}
    for s in recv.spans:
        a = agg.setdefault(s.description, [0, 0.0])
        a[0] += 1
        a[1] += s.duration_sec
    return {
        "metric": "headline phase profile (1 MLR job)",
        "epochs": epochs,
        "wall_s": round(wall, 2),
        "value": round(wall, 2),
        "unit": "s",
        "spans": {
            k: {"n": n, "total_s": round(t, 2)} for k, (n, t) in sorted(agg.items())
        },
    }


SECTIONS = {"primitives": bench_primitives, "phases": bench_phases}


METRIC_UNITS = {"primitives": ("headline primitives", "ms dispatch RTT"),
                "phases": ("headline phase profile (1 MLR job)", "s")}


def main():
    names = sys.argv[1:] or ["primitives", "phases"]
    if names == ["all"]:
        names = ["primitives", "phases"]
    unknown = [n for n in names if n not in SECTIONS]
    if unknown:
        sys.exit(f"unknown section(s) {unknown}; have {sorted(SECTIONS)} or 'all'")
    # bounded discovery BEFORE any section touches jax.devices(): on a
    # wedged transport the first device call blocks forever, and this
    # file runs unattended inside the capture bundle
    from harmony_tpu.utils.devices import discover_devices

    try:
        discover_devices()
    except RuntimeError as e:
        for n in names:
            metric, unit = METRIC_UNITS[n]
            print(json.dumps({"metric": metric, "value": None, "unit": unit,
                              "error": f"accelerator unreachable: {e}"}),
                  flush=True)
        return
    for n in names:
        print(json.dumps(SECTIONS[n]()), flush=True)


if __name__ == "__main__":
    main()
