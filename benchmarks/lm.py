#!/usr/bin/env python
"""Flagship LM benchmarks: training tokens/sec + model FLOPs utilization.

The table apps carry the reference-parity headline (bench.py); this file
measures the framework's model path — the transformer LM whose attention
runs through the framework kernels (Pallas flash on TPU, blockwise
elsewhere):

  train   single-device train step: tokens/sec, model-FLOPs/sec, MFU
          (6*N*T approximation for the training FLOPs of an N-param
          decoder, + exact attention term).
  sp      sequence-parallel train step (ring attention over a data x seq
          mesh): tokens/sec on whatever devices are visible — the
          long-context path the reference has no counterpart for.

Prints one JSON line per section. Run on a chip, or
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python benchmarks/lm.py sp
for the virtual-mesh sanity pass (CPU numbers are not chip numbers).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from harmony_tpu.utils.platform import mirror_env_platform_request

mirror_env_platform_request()  # JAX_PLATFORMS=cpu must mean cpu (axon hook)
import jax.numpy as jnp
import numpy as np

from harmony_tpu.utils.devices import discover_devices

from common import mfu as _mfu, timed_chain  # noqa: E402 (shared helpers)


def _time_chain(step, state):
    dt, _ = timed_chain(step, state, repeats=5)
    return dt


def _param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def _train_flops(n_params: int, tokens: int, cfg) -> float:
    """~6*N per token (fwd 2N + bwd 4N) + the attention term 12*L*S*d per
    token (QK^T + AV fwd and bwd, causal-halved)."""
    return tokens * (6.0 * n_params
                     + 12.0 * cfg.n_layers * cfg.max_seq * cfg.d_model / 2)


def _model(on_tpu: bool, seq: int | None = None, layers: int | None = None):
    from harmony_tpu.models import TransformerConfig, TransformerLM

    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=8192, d_model=512, n_heads=8, n_layers=layers or 8,
            d_ff=2048, max_seq=seq or 1024, attn="auto", dtype=jnp.bfloat16,
        )
    else:
        # CPU sanity shapes: the chip-sized model needs >10s per step on a
        # laptop core — these validate the path, not the number
        cfg = TransformerConfig(
            vocab_size=1024, d_model=128, n_heads=4, n_layers=layers or 2,
            d_ff=512, max_seq=seq or 256, attn="auto", dtype=jnp.float32,
        )
    return cfg, TransformerLM(cfg)


def _run_train_bench(cfg, model, batch, inner, metric, on_tpu) -> dict:
    """Shared single-device train-step timing: one raw SGD step chained
    through timed_inner's fori_loop (the ONE compile is the timed program
    itself; the dependency chain keeps the timing honest on lazy
    backends, and the fold amortizes remote-attach round trips to noise).
    Stderr markers make compile-vs-wedge visible in capture logs."""
    from harmony_tpu.models import make_lm_data

    from common import timed_inner

    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(make_lm_data(batch, cfg.max_seq, cfg.vocab_size))

    def raw_step(p):
        loss, grads = jax.value_and_grad(model.loss)(p, tokens)
        return jax.tree.map(lambda w, g: w - 0.1 * g.astype(w.dtype),
                            p, grads)

    n_params = _param_count(params)
    print(f"{metric}: compiling (params={n_params/1e6:.1f}M, "
          f"seq={cfg.max_seq}, batch={batch})...", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    dt, _ = timed_inner(raw_step, params, inner=inner, outer=3)
    print(f"{metric}: compiled+timed in {time.perf_counter() - t0:.1f}s",
          file=sys.stderr, flush=True)
    n_tok = batch * cfg.max_seq
    flops = _train_flops(n_params, n_tok, cfg)
    out = {"metric": metric, "value": round(n_tok / dt),
           "unit": "tokens/sec", "params_m": round(n_params / 1e6, 1),
           "seq": cfg.max_seq, "batch": batch,
           "tflops": round(flops / dt / 1e12, 2), "mfu": _mfu(flops / dt)}
    if not on_tpu:
        out["note"] = "cpu sanity shapes — not a chip number"
    return out


def bench_train() -> dict:
    from harmony_tpu.utils.platform import tpu_backend

    on_tpu = tpu_backend()
    cfg, model = _model(on_tpu)
    # realistic training batch: at batch 8 the 512-wide matmuls leave the
    # MXU mostly idle and the measured MFU reflects launch overhead, not
    # the model; 32x1024 tokens/step is a normal operating point
    return _run_train_bench(cfg, model, batch=32 if on_tpu else 2,
                            inner=8 if on_tpu else 1,
                            metric="lm train step", on_tpu=on_tpu)


def bench_train_100m() -> dict:
    """The SCALED flagship evidence (round-3): a ~190M-param decoder at
    seq 2048, bf16, head_dim 128, per-layer remat — the operating point
    where matmuls are large enough that MFU reflects the model, not
    launch overhead (the 29.9M/seq-1024 config measured 10.3%)."""
    from harmony_tpu.models import TransformerConfig, TransformerLM
    from harmony_tpu.utils.platform import tpu_backend

    on_tpu = tpu_backend()
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=1024, n_heads=8, n_layers=12,
            d_ff=4096, max_seq=2048, attn="auto", dtype=jnp.bfloat16,
            remat=True,
        )
        batch = 8
    else:
        # CPU sanity shape: validates the config path, not the number
        cfg = TransformerConfig(
            vocab_size=2048, d_model=256, n_heads=2, n_layers=2,
            d_ff=1024, max_seq=512, attn="auto", dtype=jnp.float32,
            remat=True,
        )
        batch = 2
    model = TransformerLM(cfg)
    out = _run_train_bench(cfg, model, batch=batch,
                           inner=4 if on_tpu else 1,
                           metric="lm train step (100M-class)",
                           on_tpu=on_tpu)
    out["remat"] = True
    return out


def bench_sp() -> dict:
    from harmony_tpu.models import make_lm_data
    from harmony_tpu.models.transformer import make_sp_train_step
    from harmony_tpu.parallel import build_mesh
    from harmony_tpu.utils.platform import tpu_backend

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        return {"metric": "lm sp train step", "value": None,
                "unit": "tokens/sec", "note": "needs >=2 devices"}
    data_ax = 2 if n % 2 == 0 else 1
    seq_ax = n // data_ax
    on_tpu = tpu_backend()
    # long-context shape: sequence scales with the ring size
    per_shard = 1024 if on_tpu else 128
    cfg, model = _model(on_tpu, seq=per_shard * seq_ax, layers=4 if on_tpu else 1)
    mesh = build_mesh(devs, data=data_ax, seq=seq_ax, model=1)
    params = model.init(jax.random.PRNGKey(0))
    batch = (2 if on_tpu else 1) * data_ax
    tokens = jnp.asarray(make_lm_data(batch, cfg.max_seq, cfg.vocab_size))
    step = make_sp_train_step(model, mesh, learning_rate=0.1, donate=False)
    dt = _time_chain(lambda p: step(p, tokens)[0], params)
    n_tok = batch * cfg.max_seq
    out = {"metric": "lm sp train step", "value": round(n_tok / dt),
           "unit": "tokens/sec", "seq": cfg.max_seq, "batch": batch,
           "mesh": {"data": data_ax, "seq": seq_ax},
           "devices": n}
    if not on_tpu:
        out["note"] = "cpu sanity shapes — not a chip number"
    return out


def bench_decode() -> dict:
    """KV-cache generation throughput (models/generate.py): one compiled
    scan for the whole continuation, no per-token host round-trips."""
    from harmony_tpu.models import make_lm_data
    from harmony_tpu.models.generate import make_generate_fn
    from harmony_tpu.utils.platform import tpu_backend

    on_tpu = tpu_backend()
    cfg, model = _model(on_tpu, seq=1024 if on_tpu else 128)
    params = model.init(jax.random.PRNGKey(0))
    batch = 8 if on_tpu else 2
    prompt_len = 32 if on_tpu else 8
    num_new = (cfg.max_seq - prompt_len) // 2
    prompt = jnp.asarray(make_lm_data(batch, prompt_len, cfg.vocab_size))
    gen = make_generate_fn(model, prompt_len, num_new)
    # chain: the next iteration's prompt is a slice of this one's output
    # (valid token ids, same shape) — keeps the loop in one device graph
    dt = _time_chain(lambda pr: gen(params, pr)[:, :prompt_len], prompt)
    # the prefill is per-token decode steps too, so the honest per-token
    # rate divides by ALL steps executed — not just the sampled ones
    # (num_new-only would skew with the prompt/continuation split)
    steps = prompt_len + num_new
    out = {"metric": "lm decode (kv cache)",
           "value": round(batch * steps / dt),
           "unit": "tokens/sec", "batch": batch, "prompt": prompt_len,
           "new_tokens": num_new,
           "ms_per_token": round(dt / steps * 1e3, 2)}
    if not on_tpu:
        out["note"] = "cpu sanity shapes — not a chip number"
    return out


def bench_pp() -> dict:
    """Pipeline-parallel train step (GPipe microbatching over a stage
    mesh, ppermute activations) — tokens/sec at 2 layers per stage."""
    from harmony_tpu.models import make_lm_data
    from harmony_tpu.models.transformer import make_pp_train_step
    from harmony_tpu.utils.platform import tpu_backend
    from jax.sharding import Mesh

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        return {"metric": "lm pp train step", "value": None,
                "unit": "tokens/sec", "note": "needs >=2 devices"}
    on_tpu = tpu_backend()
    # layers must split evenly into n stages
    cfg, model = _model(on_tpu, layers=2 * n)
    mesh = Mesh(np.asarray(devs, dtype=object).reshape(n), ("stage",))
    params = model.init(jax.random.PRNGKey(0))
    batch = (8 if on_tpu else 2) * n  # microbatch per stage
    tokens = jnp.asarray(make_lm_data(batch, cfg.max_seq, cfg.vocab_size))
    step, shard = make_pp_train_step(model, mesh, learning_rate=0.1,
                                     donate=False)
    pp_params = shard(params)
    dt = _time_chain(lambda p: step(p, tokens)[0], pp_params)
    n_tok = batch * cfg.max_seq
    out = {"metric": "lm pp train step", "value": round(n_tok / dt),
           "unit": "tokens/sec", "seq": cfg.max_seq, "batch": batch,
           "stages": n, "layers": cfg.n_layers}
    if not on_tpu:
        out["note"] = "cpu sanity shapes — not a chip number"
    return out


def bench_ep() -> dict:
    """Expert-parallel MoE train step (experts sharded over the data
    axis, all_to_all token routing) — tokens/sec."""
    from harmony_tpu.models import TransformerConfig, TransformerLM, make_lm_data
    from harmony_tpu.models.transformer import make_ep_train_step
    from harmony_tpu.parallel import build_mesh
    from harmony_tpu.utils.platform import tpu_backend

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        return {"metric": "lm ep train step", "value": None,
                "unit": "tokens/sec", "note": "needs >=2 devices"}
    on_tpu = tpu_backend()
    base, _ = _model(on_tpu)
    import dataclasses

    # replace, not a field-by-field copy: ep must benchmark exactly the
    # model the other sections use, plus the MoE fields
    cfg = dataclasses.replace(base, moe_experts=2 * n, moe_every=2)
    model = TransformerLM(cfg)
    mesh = build_mesh(devs, data=n, model=1)
    step, shard = make_ep_train_step(model, mesh, learning_rate=0.1,
                                     donate=False)
    params = shard(model.init(jax.random.PRNGKey(0)))
    batch = (8 if on_tpu else 2) * n
    tokens = jnp.asarray(make_lm_data(batch, cfg.max_seq, cfg.vocab_size))
    dt = _time_chain(lambda p: step(p, tokens)[0], params)
    n_tok = batch * cfg.max_seq
    out = {"metric": "lm ep train step", "value": round(n_tok / dt),
           "unit": "tokens/sec", "seq": cfg.max_seq, "batch": batch,
           "experts": cfg.moe_experts, "devices": n}
    if not on_tpu:
        out["note"] = "cpu sanity shapes — not a chip number"
    return out


SECTIONS = {"train": bench_train, "train100m": bench_train_100m,
            "sp": bench_sp, "decode": bench_decode,
            "pp": bench_pp, "ep": bench_ep}


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which != "all" and which not in SECTIONS:
        sys.exit(f"unknown section {which!r}; have {sorted(SECTIONS)} or 'all'")
    names = list(SECTIONS) if which == "all" else [which]
    try:
        discover_devices()
    except RuntimeError as e:
        # error lines carry the SAME metric names as success lines so
        # cross-round artifact consumers see one series in two states
        metric_names = {"train": "lm train step",
                        "train100m": "lm train step (100M-class)",
                        "sp": "lm sp train step",
                        "decode": "lm decode (kv cache)",
                        "pp": "lm pp train step", "ep": "lm ep train step"}
        for name in names:
            print(json.dumps({"metric": metric_names[name], "value": None,
                              "error": f"accelerator unreachable: {e}"}))
        return
    for name in names:
        print(json.dumps(SECTIONS[name]()))


if __name__ == "__main__":
    main()
