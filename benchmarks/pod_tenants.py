#!/usr/bin/env python
"""Concurrent multi-tenant pod: per-tenant slowdown on disjoint carves.

The round-2 verdict's top item: jobs must overlap ACROSS the pod, not
serialize behind a pod lock. This artifact measures what that buys on a
virtual 2-process/8-device pod with the pod_carve scheduler (each tenant
gets one whole process): two MLR tenants run first in isolation, then
concurrently, all in one pod session (warmup jobs populate both
processes' program caches first so compile time doesn't masquerade as
contention). Reported per tenant: wall seconds isolated vs concurrent,
slowdown, plus Jain's fairness index over the slowdowns, the concurrent
walls' overlap, and aggregate throughput. CPU-mesh numbers — comparable
across rounds, not to a chip.

Writes benchmarks/POD_TENANTS_r03.json; prints ONE JSON line.
Run: python benchmarks/pod_tenants.py
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from common import free_port, sanitized_cpu_env, wait_for_ready  # noqa: E402

EPOCHS = 8
BATCHES = 4
N = 16384
METRIC = "pod concurrent-tenant slowdown (2-process carved pod, MLR x2)"
OUT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "POD_TENANTS_r03.json")


def _job(job_id: str, seed: int, epochs: int = EPOCHS):
    from harmony_tpu.config.params import JobConfig, TrainerParams

    return JobConfig(
        job_id=job_id, app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=BATCHES,
            app_params={"num_classes": 32, "num_features": 512,
                        "features_per_partition": 64, "step_size": 0.05},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": N, "num_features": 512,
                            "num_classes": 32, "seed": seed}},
    )


def _drain(sender, deadline: float) -> bool:
    while time.monotonic() < deadline:
        if not sender.send_status_command().get("running"):
            return True
        time.sleep(0.3)
    return False


def main() -> None:
    worker = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "pod_worker.py")
    env = sanitized_cpu_env(4)
    coord, pod_port, tcp_port = free_port(), free_port(), free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"127.0.0.1:{coord}", "2", str(pid),
             str(pod_port), str(tcp_port), "pod_carve:1"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    out = {"metric": METRIC, "unit": "x slowdown (concurrent/isolated)",
           "processes": 2, "global_devices": 8}
    try:
        if not wait_for_ready(procs[0], 240):
            out.update(value=None, error="leader not ready within 240s")
            print(json.dumps(out))
            return

        from harmony_tpu.jobserver.client import CommandSender

        sender = CommandSender(tcp_port)
        deadline = time.monotonic() + 1800

        def submit(cfgs):
            for cfg in cfgs:
                resp = sender.send_job_submit_command(cfg)
                if not resp.get("ok"):
                    raise RuntimeError(f"submit failed: {resp}")
            if not _drain(sender, deadline):
                raise RuntimeError("drain timed out")

        # 1. concurrent warmups: compile the MLR step on BOTH processes
        submit([_job("warm-a", seed=9, epochs=1),
                _job("warm-b", seed=8, epochs=1)])
        # 2. isolated timed runs (sequential; warm program caches)
        submit([_job("iso-a", seed=1)])
        submit([_job("iso-b", seed=2)])
        # 3. concurrent timed runs
        submit([_job("conc-a", seed=1), _job("conc-b", seed=2)])

        sender.send_shutdown_command()
        lead_out, _ = procs[0].communicate(timeout=120)
        procs[1].communicate(timeout=120)
    except Exception as e:  # noqa: BLE001 - still print one line
        out.update(value=None, error=f"{type(e).__name__}: {e}")
        print(json.dumps(out))
        return
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    result_lines = [ln for ln in lead_out.splitlines()
                    if ln.startswith("RESULT ")]
    if not result_lines:
        out.update(value=None, error="no RESULT from leader")
        print(json.dumps(out))
        return
    res = json.loads(result_lines[0][len("RESULT "):])
    for jid in ("iso-a", "iso-b", "conc-a", "conc-b"):
        job = res.get("local_results", {}).get(jid, {})
        if "error" in job:
            out.update(value=None, error=f"{jid} failed: {job['error']}")
            print(json.dumps(out))
            return
    walls = res["job_walls"]
    iso = {t: walls[f"iso-{t}"][1] - walls[f"iso-{t}"][0] for t in "ab"}
    conc = {t: walls[f"conc-{t}"][1] - walls[f"conc-{t}"][0] for t in "ab"}
    slow = {t: conc[t] / iso[t] for t in "ab"}
    overlap = (min(walls["conc-a"][1], walls["conc-b"][1])
               - max(walls["conc-a"][0], walls["conc-b"][0]))
    vals = list(slow.values())
    jain = sum(vals) ** 2 / (len(vals) * sum(v * v for v in vals))
    conc_wall = (max(walls["conc-a"][1], walls["conc-b"][1])
                 - min(walls["conc-a"][0], walls["conc-b"][0]))
    detail = {
        "host_cores": os.cpu_count(),
        "note": (
            "both pod processes share ONE host's cores in this virtual "
            "setup, so per-tenant slowdown is floored at ~n_tenants x on a "
            "saturated host; the signals that transfer to real multi-host "
            "pods are jain_fairness (equal degradation, no starvation) and "
            "concurrent_overlap_sec > 0 (true cross-pod overlap)"
        ),
        "isolated_wall_sec": {t: round(iso[t], 2) for t in "ab"},
        "concurrent_wall_sec": {t: round(conc[t], 2) for t in "ab"},
        "slowdown": {t: round(slow[t], 3) for t in "ab"},
        "jain_fairness": round(jain, 3),
        "concurrent_overlap_sec": round(overlap, 2),
        "aggregate_samples_per_sec_concurrent": round(
            2 * EPOCHS * N / conc_wall, 1),
        "epochs": EPOCHS, "examples_per_tenant": N,
        "scheduler": "pod_carve:1",
    }
    out.update(value=round(max(vals), 3), **detail)
    with open(OUT_PATH, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
