#!/usr/bin/env python
"""Concurrent multi-tenant pod: per-tenant slowdown, carve AND share-all.

Round-2's verdict demanded overlap ACROSS the pod on disjoint carves;
round-3's demanded the reference's DEFAULT mode — every job on ALL
executors simultaneously (SchedulerImpl.java:28-66), made safe by the
cross-job unit protocol (runtime/podunits.py). This artifact measures
both on a virtual 2-process/8-device pod with two MLR tenants: isolated
runs first, then concurrent, per scheduler mode (warmups populate the
program caches so compile never masquerades as contention). Reported per
mode: per-tenant walls, slowdowns, Jain's index, concurrent overlap, and
aggregate throughput. CPU-mesh numbers — comparable across rounds, not
to a chip.

Writes benchmarks/POD_TENANTS_<suffix>.json (argv[1], default r05); prints ONE JSON line.
Run: python benchmarks/pod_tenants.py
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from common import free_port, sanitized_cpu_env, wait_for_ready  # noqa: E402

EPOCHS = 8
BATCHES = 4
N = 16384
METRIC = "pod concurrent-tenant slowdown (2-process pod, MLR x2)"
OUT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    f"POD_TENANTS_{sys.argv[1] if len(sys.argv) > 1 else 'r05'}.json")


def _job(job_id: str, seed: int, epochs: int = EPOCHS):
    from harmony_tpu.config.params import JobConfig, TrainerParams

    return JobConfig(
        job_id=job_id, app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=BATCHES,
            app_params={"num_classes": 32, "num_features": 512,
                        "features_per_partition": 64, "step_size": 0.05},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": N, "num_features": 512,
                            "num_classes": 32, "seed": seed}},
    )


def _drain(sender, deadline: float) -> bool:
    while time.monotonic() < deadline:
        if not sender.send_status_command().get("running"):
            return True
        time.sleep(0.3)
    return False


def _run_mode(scheduler: str) -> dict:
    """One pod session under ``scheduler``: warmup, isolated runs,
    concurrent run; returns the measured section dict (raises on any
    job/infra failure)."""
    worker = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "pod_worker.py")
    env = sanitized_cpu_env(4)
    coord, pod_port, tcp_port = free_port(), free_port(), free_port()
    args_tail = [str(pod_port), str(tcp_port)]
    if scheduler != "-":
        args_tail.append(scheduler)
    errs = [open(os.path.join(HERE := os.path.dirname(
        os.path.abspath(__file__)), f".pod_tenants_p{pid}.err"), "w")
        for pid in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"127.0.0.1:{coord}", "2", str(pid),
             *args_tail],
            stdout=subprocess.PIPE, stderr=errs[pid], text=True,
            env=env,
        )
        for pid in range(2)
    ]
    try:
        if not wait_for_ready(procs[0], 240):
            raise RuntimeError("leader not ready within 240s")

        from harmony_tpu.jobserver.client import CommandSender

        sender = CommandSender(tcp_port)
        deadline = time.monotonic() + 1800

        def submit(cfgs):
            for cfg in cfgs:
                resp = sender.send_job_submit_command(cfg)
                if not resp.get("ok"):
                    raise RuntimeError(f"submit failed: {resp}")
            if not _drain(sender, deadline):
                raise RuntimeError("drain timed out")

        # 1. concurrent warmups: SAME epochs and seeds as the timed runs,
        # so the timed phases find hot programs (incl. the multi-epoch
        # window variant) AND device-resident datasets — otherwise the
        # isolated phase pays one-time uploads/compiles the concurrent
        # phase inherits and "slowdown" drops below 1
        submit([_job("warm-a", seed=1), _job("warm-b", seed=2)])
        # 2. isolated timed runs (sequential; warm program caches)
        submit([_job("iso-a", seed=1)])
        submit([_job("iso-b", seed=2)])
        # 3. concurrent timed runs
        submit([_job("conc-a", seed=1), _job("conc-b", seed=2)])

        sender.send_shutdown_command()
        lead_out, _ = procs[0].communicate(timeout=120)
        procs[1].communicate(timeout=120)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    result_lines = [ln for ln in lead_out.splitlines()
                    if ln.startswith("RESULT ")]
    if not result_lines:
        raise RuntimeError("no RESULT from leader")
    res = json.loads(result_lines[0][len("RESULT "):])
    for jid in ("iso-a", "iso-b", "conc-a", "conc-b"):
        job = res.get("local_results", {}).get(jid, {})
        if "error" in job:
            raise RuntimeError(f"{jid} failed: {job['error']}")
    walls = res["job_walls"]
    iso = {t: walls[f"iso-{t}"][1] - walls[f"iso-{t}"][0] for t in "ab"}
    conc = {t: walls[f"conc-{t}"][1] - walls[f"conc-{t}"][0] for t in "ab"}
    slow = {t: conc[t] / iso[t] for t in "ab"}
    overlap = (min(walls["conc-a"][1], walls["conc-b"][1])
               - max(walls["conc-a"][0], walls["conc-b"][0]))
    vals = list(slow.values())
    jain = sum(vals) ** 2 / (len(vals) * sum(v * v for v in vals))
    conc_wall = (max(walls["conc-a"][1], walls["conc-b"][1])
                 - min(walls["conc-a"][0], walls["conc-b"][0]))
    return {
        "isolated_wall_sec": {t: round(iso[t], 2) for t in "ab"},
        "concurrent_wall_sec": {t: round(conc[t], 2) for t in "ab"},
        "slowdown": {t: round(slow[t], 3) for t in "ab"},
        "max_slowdown": round(max(vals), 3),
        "jain_fairness": round(jain, 3),
        "concurrent_overlap_sec": round(overlap, 2),
        "aggregate_samples_per_sec_concurrent": round(
            2 * EPOCHS * N / conc_wall, 1),
    }


def main() -> None:
    out = {"metric": METRIC, "unit": "x slowdown (concurrent/isolated)",
           "processes": 2, "global_devices": 8,
           "epochs": EPOCHS, "examples_per_tenant": N,
           "host_cores": os.cpu_count(),
           "note": (
               "both pod processes share ONE host's cores in this virtual "
               "setup, so per-tenant slowdown is floored at ~n_tenants x "
               "on a saturated host; the signals that transfer to real "
               "multi-host pods are jain_fairness (equal degradation, no "
               "starvation) and concurrent_overlap_sec > 0 (true "
               "cross-pod overlap). share_all = both tenants on the SAME "
               "2-process 8-device mesh, interleaved by the cross-job "
               "unit protocol; carve = disjoint whole-process slices."
           )}
    try:
        out["carve"] = _run_mode("pod_carve:1")
        out["share_all"] = _run_mode("-")
        out["value"] = out["share_all"]["max_slowdown"]
    except Exception as e:  # noqa: BLE001 - still print one line
        out.update(value=None, error=f"{type(e).__name__}: {e}")
        print(json.dumps(out))
        return
    with open(OUT_PATH, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
