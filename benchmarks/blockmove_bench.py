#!/usr/bin/env python
"""Cross-process block-migration bandwidth — parallel vs serial legs.

Round-4's verdict flagged the old cross-process reshard (full-table
replicate + host round-trip) as the elasticity ceiling; round 5 replaced
it with point-to-point block moves (table/blockmove.py). This round makes
the exchange CONCURRENT (HARMONY_MOVE_PARALLEL legs + split streams), so
the bench drives the TRANSPORT LAYER itself — ``_tcp_exchange`` with a
synthetic MovePlan across 3 REAL processes rendezvousing through the jax
coordination KV store — serial (=1) and parallel (=4), interleaved
rounds, best-of per arm. (The table-level reshard wrapper needs
multi-process SPMD computations, which this host's jax CPU backend
cannot run — see ROADMAP; the transport is exactly the layer this round
parallelized, and every received block is verified byte-identical to the
payload in BOTH modes before a number is reported.)

Directions:
  * grow: proc 0 streams half the table to proc 1 and half to proc 2 —
    the MULTI-PEER send direction: serial sends the legs one after the
    other, parallel overlaps them (splitting oversized legs into
    striped streams);
  * shrink: procs 1+2 each stream their half back to proc 0
    (multi-source receive).

Prints ONE JSON line. Run: python benchmarks/blockmove_bench.py
"""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import free_port, sanitized_cpu_env  # noqa: E402

NPROCS = 3
NB, ROWS, DIM = 128, 1024, 256   # 128 x 1 MB blocks = 128 MB moved/direction
ROUNDS = 3

WORKER = r'''
import json, os, sys, time
sys.path.insert(0, sys.argv[4])
def main():
    coordinator, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    from harmony_tpu.parallel import multihost
    assert multihost.initialize_distributed(coordinator, nprocs, pid)
    import numpy as np
    from harmony_tpu.table import blockmove
    NB, ROWS, DIM = %d, %d, %d
    base = np.arange(ROWS * DIM, dtype=np.float32).reshape(ROWS, DIM)
    def block(b):
        return base + b
    # grow: pid 0 -> peers 1 and 2 (multi-peer send)
    plan_g = blockmove.MovePlan(
        sends={0: [(b, 1 + (b %% 2)) for b in range(NB)]},
        recvs={1: {b for b in range(NB) if b %% 2 == 0},
               2: {b for b in range(NB) if b %% 2 == 1}},
        block_nbytes=base.nbytes,
    )
    out_g = {b: block(b) for b in range(NB)} if pid == 0 else {}
    t0 = time.perf_counter()
    recv, sent = blockmove._tcp_exchange(plan_g, out_g, 1)
    grow_s = time.perf_counter() - t0
    for b, a in recv.items():
        assert np.array_equal(a, block(b)), f"grow parity broke at {b}"
    # shrink: peers 1 and 2 -> pid 0 (multi-source receive)
    plan_s = blockmove.MovePlan(
        sends={1: [(b, 0) for b in range(NB) if b %% 2 == 0],
               2: [(b, 0) for b in range(NB) if b %% 2 == 1]},
        recvs={0: set(range(NB))},
        block_nbytes=base.nbytes,
    )
    out_s = ({b: block(b) for b in range(NB) if (b %% 2) + 1 == pid}
             if pid else {})
    t0 = time.perf_counter()
    recv, sent2 = blockmove._tcp_exchange(plan_s, out_s, 2)
    shrink_s = time.perf_counter() - t0
    for b, a in recv.items():
        assert np.array_equal(a, block(b)), f"shrink parity broke at {b}"
    print("RESULT " + json.dumps({
        "pid": pid, "grow_s": round(grow_s, 3),
        "shrink_s": round(shrink_s, 3),
        "moved": int(sent + sent2
                     + sum(a.nbytes for a in recv.values())
                     + (len(plan_g.recvs.get(pid, ())) * base.nbytes)),
    }), flush=True)
main()
''' % (NB, ROWS, DIM)


def _paced_plan_json() -> str:
    """A deterministic per-block wire-time injection (5 ms at every
    blockmove.send hit — a 1 MB block at ~200 MB/s per stream, the
    realistic single-TCP-stream DCN rate) via the PR-2 fault harness:
    the bench-only DCN pacing emulation, same spirit as
    HARMONY_POD_UNIT_LAT_MS. Loopback has no wire time at all, so the
    'local' arm measures only protocol CPU (bounded by this host's core
    quota); the paced arm measures the latency-bound regime real DCN
    streams live in, where overlapping legs is the whole point."""
    from harmony_tpu.faults import FaultPlan, FaultRule

    return FaultPlan([FaultRule(
        "blockmove.send", action="delay", delay_sec=0.005, count=-1,
    )]).to_json()


def run_pod(parallel: int, paced: bool) -> "dict":
    """One 3-process pass at HARMONY_MOVE_PARALLEL=parallel; returns
    {grow_s, shrink_s} as the max across processes (the exchange is done
    when the last participant is)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = sanitized_cpu_env(1)
    env["HARMONY_MOVE_PARALLEL"] = str(parallel)
    if paced:
        env["HARMONY_FAULT_PLAN"] = _paced_plan_json()
    else:
        env.pop("HARMONY_FAULT_PLAN", None)
    port = free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, f"127.0.0.1:{port}",
             str(NPROCS), str(pid), repo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in range(NPROCS)
    ]
    rows = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(f"worker failed: {err[-500:]}")
            line = [ln for ln in out.splitlines()
                    if ln.startswith("RESULT ")]
            rows.append(json.loads(line[0][len("RESULT "):]))
    except Exception:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    return {"grow_s": max(r["grow_s"] for r in rows),
            "shrink_s": max(r["shrink_s"] for r in rows)}


def main() -> None:
    moved_mb = NB * ROWS * DIM * 4 / 1e6
    arms = {}
    try:
        for profile, paced in (("local", False), ("paced_dcn", True)):
            best = {1: {"grow_s": None, "shrink_s": None},
                    4: {"grow_s": None, "shrink_s": None}}
            # interleaved rounds, best-of per arm: this host's throughput
            # drifts round to round, so serial and parallel alternate
            # inside every round instead of running as two blocks
            for _ in range(ROUNDS):
                for par in (1, 4):
                    got = run_pod(par, paced)
                    for k, v in got.items():
                        cur = best[par][k]
                        best[par][k] = v if cur is None else min(cur, v)
            serial, parallel = best[1], best[4]
            arms[profile] = {
                "serial": {k: round(v, 3) for k, v in serial.items()},
                "parallel": {k: round(v, 3) for k, v in parallel.items()},
                "speedup_grow": round(
                    serial["grow_s"] / parallel["grow_s"], 2),
                "speedup_shrink": round(
                    serial["shrink_s"] / parallel["shrink_s"], 2),
            }
    except Exception as e:  # noqa: BLE001 - one JSON line, always
        print(json.dumps({
            "metric": "cross-process block migration, parallel vs serial legs",
            "value": None, "unit": "MB/s moved (grow, parallel)",
            "error": f"{type(e).__name__}: {e}"[:300],
        }))
        return
    print(json.dumps({
        "metric": "cross-process block migration, parallel vs serial legs",
        "value": round(moved_mb / arms["local"]["parallel"]["grow_s"], 1),
        "unit": "MB/s moved (grow, parallel, local)",
        "moved_mb": round(moved_mb, 1), "blocks": NB, "procs": NPROCS,
        "rounds": ROUNDS,
        "local": arms["local"],
        "paced_dcn": arms["paced_dcn"],
        "transport": "tcp",
        "note": ("3 real processes, loopback TCP, jax-KV rendezvous; "
                 "transport layer only (this host's CPU backend cannot "
                 "run the multi-process SPMD rebuild — see ROADMAP). "
                 "Every received block verified byte-identical in both "
                 "modes; grow = multi-peer send (HARMONY_MOVE_PARALLEL=4 "
                 "overlaps per-peer legs + splits oversized legs into "
                 "striped streams). 'local' is pure protocol CPU and is "
                 "capped by this host's ~2-core quota (thread scaling "
                 "ceiling ~1.4x measured); 'paced_dcn' injects a "
                 "deterministic 5 ms/block wire time at blockmove.send "
                 "(fault-harness delay rule, HARMONY_POD_UNIT_LAT_MS "
                 "precedent) — the latency-bound regime real DCN streams "
                 "occupy, where overlapped legs shine"),
    }))


if __name__ == "__main__":
    main()
