#!/usr/bin/env python
"""Cross-process block-migration bandwidth — the NEW move path's number.

Round-4's verdict flagged the old cross-process reshard (full-table
replicate + host round-trip) as the elasticity ceiling; this measures
its replacement (table/blockmove.py) end to end on a 2-process virtual
pod: a 512-block, 64 MB dense table shrinks onto process 0's devices
and grows back, point-to-point over the TCP DCN channel. Reported:
moved bytes (exactly half the table per direction — the O(moved)
contract), wall per direction, and effective bandwidth over the moved
bytes. Loopback numbers — the protocol/assembly cost floor, not DCN.

Prints ONE JSON line. Run: python benchmarks/blockmove_bench.py
"""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import free_port, sanitized_cpu_env  # noqa: E402

NB, CAP, DIM = 512, 16384, 1024  # 16384 x 1024 x f32 = 64 MB

WORKER = r'''
import json, os, sys, time
sys.path.insert(0, sys.argv[4])
def main():
    coordinator, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    from harmony_tpu.parallel import multihost
    assert multihost.initialize_distributed(coordinator, nprocs, pid)
    import jax, numpy as np
    from harmony_tpu.parallel.mesh import build_mesh
    from harmony_tpu.config.params import TableConfig
    from harmony_tpu.table.table import DenseTable, TableSpec
    from harmony_tpu.table import blockmove
    NB, CAP, DIM = %d, %d, %d
    devs = jax.devices()
    mesh_a = build_mesh(devs, data=1, model=len(devs))
    mesh_b = build_mesh(devs[:len(devs) // 2], data=1,
                        model=len(devs) // 2)
    cfg = TableConfig(table_id="bm", capacity=CAP, value_shape=(DIM,),
                      num_blocks=NB)
    t = DenseTable(TableSpec(cfg), mesh_a)
    keys = np.arange(CAP)
    vals = (np.arange(DIM, dtype=np.float32)[None, :]
            + keys[:, None]).astype(np.float32)
    t.multi_put(keys, vals)
    t0 = time.perf_counter(); t.reshard(mesh_b)
    shrink_s = time.perf_counter() - t0
    st = dict(blockmove.last_move_stats)
    t0 = time.perf_counter(); t.reshard(mesh_a)
    grow_s = time.perf_counter() - t0
    st2 = dict(blockmove.last_move_stats)
    mine = t.addressable_blocks()
    ok = all(np.allclose(mine[b][0], vals[b * (CAP // NB)])
             for b in list(mine)[:8])
    print("RESULT " + json.dumps({
        "pid": pid, "ok": bool(ok),
        "shrink_s": round(shrink_s, 3), "grow_s": round(grow_s, 3),
        "shrink_moved": st.get("bytes_sent", 0)
                        + st.get("bytes_received", 0),
        "grow_moved": st2.get("bytes_sent", 0)
                      + st2.get("bytes_received", 0),
        "transport": st.get("transport"),
    }), flush=True)
main()
''' % (NB, CAP, DIM)


def main() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = sanitized_cpu_env(4)
    port = free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, f"127.0.0.1:{port}", "2",
             str(pid), repo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    rows = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(f"worker failed: {err[-500:]}")
            line = [ln for ln in out.splitlines()
                    if ln.startswith("RESULT ")]
            rows.append(json.loads(line[0][len("RESULT "):]))
    except Exception as e:  # noqa: BLE001 - one JSON line, always
        for p in procs:
            if p.poll() is None:
                p.kill()
        print(json.dumps({
            "metric": "cross-process block migration bandwidth",
            "value": None, "unit": "MB/s moved",
            "error": f"{type(e).__name__}: {e}"[:300],
        }))
        return
    assert all(r["ok"] for r in rows), rows
    table_mb = CAP * DIM * 4 / 1e6
    moved_mb = rows[0]["shrink_moved"] / 1e6  # same plan on both procs
    wall = max(r["shrink_s"] for r in rows)
    grow_wall = max(r["grow_s"] for r in rows)
    print(json.dumps({
        "metric": "cross-process block migration bandwidth",
        "value": round(moved_mb / wall, 1), "unit": "MB/s moved",
        "table_mb": round(table_mb, 1), "moved_mb": round(moved_mb, 1),
        "blocks": NB, "shrink_s": round(wall, 3),
        "grow_s": round(grow_wall, 3),
        "grow_mbps": round(moved_mb / grow_wall, 1),
        "transport": rows[0]["transport"],
        "note": ("2-process virtual pod, loopback TCP: the protocol + "
                 "assembly cost floor. Moved bytes are exactly half the "
                 "table per direction (the O(moved) contract) — the old "
                 "path replicated the WHOLE table per device"),
    }))


if __name__ == "__main__":
    main()
