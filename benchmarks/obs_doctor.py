#!/usr/bin/env python
"""Telemetry-history + doctor overhead benchmark (PR 11).

The scraper and doctor run INSIDE the jobserver at
``HARMONY_OBS_SCRAPE_PERIOD`` cadence, stealing cycles from the control
plane — so their cost is measured, not assumed. Three stages:

1. **scrape round-trip** — a real HTTP scrape of a populated exporter
   through the hardened :class:`ScrapeClient` (wire + parse);
2. **ingest** — folding one parsed exposition into the store, swept
   over target counts (the leader scrapes every pod follower);
3. **diagnose** — one full rule-catalog evaluation, swept over tenant
   counts with scenario-shaped series (every rule has real work).

Prints ONE JSON document; the committed capture is
``benchmarks/OBS_DOCTOR_r<N>.json``. Pure CPU/stdlib — comparable
across rounds regardless of accelerator health.

Usage: python benchmarks/obs_doctor.py [--rounds N]
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def _populate(reg, families: int = 30, cells: int = 8) -> None:
    """A registry shaped like a busy worker's: a few dozen families,
    a handful of label cells each, one histogram in three."""
    for i in range(families):
        if i % 3 == 0:
            h = reg.histogram(f"harmony_bench_f{i}_seconds", "bench")
            for j in range(cells):
                h.observe(0.01 * (j + 1))
        elif i % 3 == 1:
            c = reg.counter(f"harmony_bench_f{i}_total", "bench",
                            ("op",))
            for j in range(cells):
                c.labels(op=f"op{j}").inc(j + 1)
        else:
            g = reg.gauge(f"harmony_bench_f{i}", "bench", ("job",))
            for j in range(cells):
                g.labels(job=f"j{j}").set(float(j))


def bench_scrape(rounds: int) -> dict:
    from harmony_tpu.metrics.exporter import MetricsExporter
    from harmony_tpu.metrics.history import HistoryStore, ScrapeClient
    from harmony_tpu.metrics.registry import MetricRegistry

    reg = MetricRegistry()
    _populate(reg)
    exp = MetricsExporter(0, registry=reg).start()
    client = ScrapeClient()
    store = HistoryStore(window_sec=900.0, resolution_sec=1.0)
    try:
        samples = []
        text = ""
        for i in range(rounds):
            t0 = time.perf_counter()
            text = client.fetch("bench", exp.url + "/metrics")
            store.ingest_exposition("bench", text,
                                    ts=time.time() - rounds + i)
            samples.append((time.perf_counter() - t0) * 1000.0)
    finally:
        exp.stop()
    return {
        "roundtrip_ms": round(statistics.median(samples), 3),
        "scrape_bytes": len(text),
        "series": store.stats()["series"],
    }


def bench_ingest(rounds: int) -> dict:
    from harmony_tpu.metrics.history import HistoryStore
    from harmony_tpu.metrics.registry import MetricRegistry, parse_exposition

    reg = MetricRegistry()
    _populate(reg)
    families = parse_exposition(reg.expose())
    out = {}
    for targets in (1, 4, 16):
        store = HistoryStore(window_sec=900.0, resolution_sec=1.0)
        samples = []
        for r in range(rounds):
            ts = time.time() - rounds + r
            t0 = time.perf_counter()
            for t in range(targets):
                store.ingest_exposition(f"pod:{t}", families, ts=ts)
            samples.append((time.perf_counter() - t0) * 1000.0)
        st = store.stats()
        out[f"targets_{targets}"] = {
            "cycle_ms": round(statistics.median(samples), 3),
            "series": st["series"],
            "points": st["points"],
        }
    return out


def _scenario_store(tenants: int):
    from harmony_tpu.metrics.history import HistoryStore

    store = HistoryStore(window_sec=900.0, resolution_sec=1.0)
    now = time.time()
    for j in range(tenants):
        labels = {"job": f"t{j}", "attempt": f"t{j}"}
        for i in range(60):
            ts = now - 60 + i
            store.ingest("tenant.input_wait_frac", labels,
                         0.8 if j % 2 else 0.1, ts=ts)
            store.ingest("tenant.straggler_ratio", labels,
                         2.5 if j % 3 == 0 else 1.0, ts=ts)
            store.ingest("tenant.mfu", labels,
                         0.4 if i < 30 else 0.1, ts=ts)
            store.ingest("tenant.samples_per_sec", labels,
                         1000.0 - i, ts=ts)
    store.ingest("harmony_table_layout_changes_total",
                 {"target": "leader"}, 1.0, ts=now - 50, kind="counter",
                 target="leader")
    store.ingest("harmony_table_layout_changes_total",
                 {"target": "leader"}, 3.0, ts=now - 10, kind="counter",
                 target="leader")
    return store


def bench_diagnose(rounds: int) -> dict:
    from harmony_tpu.metrics.doctor import Doctor, all_rules

    out = {"rules": len(all_rules())}
    for tenants in (2, 8, 32):
        store = _scenario_store(tenants)
        doc = Doctor(store, events_fn=dict)
        samples = []
        fired = 0
        for _ in range(rounds):
            t0 = time.perf_counter()
            fired += len(doc.diagnose())
            samples.append((time.perf_counter() - t0) * 1000.0)
        out[f"tenants_{tenants}"] = {
            "eval_ms": round(statistics.median(samples), 3),
            "series": store.stats()["series"],
            "diagnoses_emitted": fired,
        }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="obs_doctor bench")
    ap.add_argument("--rounds", type=int, default=20)
    args = ap.parse_args(argv)
    line = {
        "metric": "telemetry-history ingest + doctor rule-evaluation "
                  "overhead per scrape cycle",
        "unit": "ms (median)",
        "rounds": args.rounds,
        "scrape": bench_scrape(args.rounds),
        "ingest": bench_ingest(args.rounds),
        "diagnose": bench_diagnose(args.rounds),
    }
    print(json.dumps(line, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
