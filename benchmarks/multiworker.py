#!/usr/bin/env python
"""Multi-worker / multi-tenant aggregate on the VIRTUAL 8-device mesh.

bench.py (the driver-run headline) measures the concurrent MLR+NMF+LDA
aggregate with num_workers=1 per job — on one real chip that is the whole
machine. This companion records the same three jobs with the MULTI-WORKER
machinery engaged (SSP mini-batch controller, worker state barriers,
per-worker data splits) over the 8-virtual-CPU mesh, so the round also
carries a number for the sharing mode the reference's north star actually
describes (BASELINE.md config 4; SchedulerImpl runs every job on all
executors). Numbers are CPU-mesh numbers — comparable across rounds, not
to the chip.

Prints ONE JSON line. Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python benchmarks/multiworker.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The metric name hardcodes "8-device virtual mesh": force the 8 virtual
# devices ourselves (must happen before jax import) so a bare run can't
# silently record a 1-device sample into the same series.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

from harmony_tpu.config.params import JobConfig, TrainerParams  # noqa: E402
from harmony_tpu.jobserver.server import JobServer  # noqa: E402
from harmony_tpu.parallel.mesh import DevicePool  # noqa: E402

EPOCHS = 4
BATCHES = 4
WORKERS = 4  # per job, SSP slack 1


def _cfg(job_id, trainer, app_params, data_fn, data_args, n):
    return JobConfig(
        job_id=job_id, app_type="dolphin", trainer=trainer,
        params=TrainerParams(num_epochs=EPOCHS, num_mini_batches=BATCHES,
                             clock_slack=1, app_params=app_params),
        num_workers=WORKERS,
        user={"data_fn": data_fn, "data_args": data_args},
    ), EPOCHS * n


def main() -> None:
    devices = jax.devices()[:8]
    assert len(devices) == 8, f"need 8 virtual devices, have {len(devices)}"
    mlr_n, nmf_rows, lda_docs = 2048, 512, 256
    jobs = [
        _cfg("mw-mlr", "harmony_tpu.apps.mlr:MLRTrainer",
             {"num_classes": 64, "num_features": 1024,
              "features_per_partition": 128, "step_size": 0.05},
             "harmony_tpu.apps.mlr:make_synthetic",
             {"n": mlr_n, "num_features": 1024, "num_classes": 64}, mlr_n),
        _cfg("mw-nmf", "harmony_tpu.apps.nmf:NMFTrainer",
             {"num_rows": nmf_rows, "num_cols": 1024, "rank": 64,
              "step_size": 0.01},
             "harmony_tpu.apps.nmf:make_synthetic",
             {"num_rows": nmf_rows, "num_cols": 1024, "rank": 64}, nmf_rows),
        _cfg("mw-lda", "harmony_tpu.apps.lda:LDATrainer",
             {"vocab_size": 1024, "num_topics": 16, "num_docs": lda_docs,
              "max_doc_len": 64},
             "harmony_tpu.apps.lda:make_synthetic",
             {"num_docs": lda_docs, "vocab_size": 1024, "num_topics": 16,
              "doc_len": 64}, lda_docs),
    ]
    server = JobServer(num_executors=8, device_pool=DevicePool(devices))
    server.start()
    try:
        t0 = time.perf_counter()
        futures = [server.submit(c) for c, _ in jobs]
        for f in futures:
            f.result(timeout=1800)
        wall = time.perf_counter() - t0
    finally:
        server.shutdown(timeout=120)
    total = sum(n for _, n in jobs)
    print(json.dumps({
        "metric": "multi-worker aggregate, concurrent MLR+NMF+LDA "
                  "(8-device virtual mesh)",
        "value": round(total / wall, 1),
        "unit": "samples/sec",
        "workers_per_job": WORKERS,
        "ssp_slack": 1,
        "devices": len(devices),
        "wall_sec": round(wall, 1),
    }))


if __name__ == "__main__":
    main()
