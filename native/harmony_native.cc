// harmony_tpu native runtime pieces (C ABI, loaded via ctypes).
//
// The reference reaches native code only through library JNI (SURVEY.md
// §5.9): BLAS via Breeze/netlib (→ XLA here), the Netty transport, and the
// Hadoop HDFS client for data loading + checkpoint commit. This file is the
// TPU build's equivalent of the latter two host-side data planes:
//
//   * ht_parse_libsvm — the data-loader hot loop (text records → dense
//     feature matrix), ~20-40x the CPython per-token cost of the pure-Python
//     parser (ref path: HdfsSplitFetcher.fetchData → DataParser).
//   * ht_blk_write / ht_blk_read — per-block checkpoint files with a CRC32
//     integrity footer, the durable-commit analogue of ChkpManagerSlave's
//     temp→HDFS two-stage files (evaluator/impl/ChkpManagerSlave.java:50-63).
//     Read verifies the checksum so a torn/corrupt block fails restore
//     loudly instead of feeding garbage into a model table.
//
// Build: g++ -O3 -shared -fPIC (driven lazily by harmony_tpu/native).
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32 (IEEE 802.3, table-driven)
// ---------------------------------------------------------------------------

static uint32_t crc_table[256];
static int crc_ready = 0;

static void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_ready = 1;
}

uint32_t ht_crc32(const uint8_t* data, uint64_t len) {
  if (!crc_ready) crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (uint64_t i = 0; i < len; i++)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// LibSVM parsing: "label idx:val idx:val ...\n" → dense x [rows, F] + y
// ---------------------------------------------------------------------------

static inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
  return p;
}

// Returns number of rows parsed, -1 if more than max_rows lines present,
// or -2 on a malformed record (unparseable label or feature token) — strict
// parity with the Python parser, which raises on corrupt data instead of
// silently training on it. Out-of-range feature indices are ignored (also
// parity).
int64_t ht_parse_libsvm(const char* buf, uint64_t len, int32_t num_features,
                        int32_t base, float* x, float* y, int64_t max_rows) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t row = 0;
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') { p++; continue; }  // blank line
    if (row >= max_rows) return -1;
    char* next = nullptr;
    y[row] = strtof(p, &next);
    if (next == p) return -2;  // label is not a number
    p = next;
    float* xrow = x + (uint64_t)row * num_features;
    while (p < end && *p != '\n') {
      p = skip_ws(p, end);
      if (p >= end || *p == '\n') break;
      long idx = strtol(p, &next, 10);
      if (next == p || next >= end || *next != ':') return -2;
      p = next + 1;  // past ':'
      float val = strtof(p, &next);
      if (next == p) return -2;  // "idx:" with no value
      p = next;
      long j = idx - base;
      if (j >= 0 && j < num_features) xrow[j] = val;
    }
    if (p < end) p++;  // consume '\n'
    row++;
  }
  return row;
}

// ---------------------------------------------------------------------------
// Block files.
//   v1 "HTB1": [magic u32][dtype u32][ndim u32][shape u64 x ndim]
//              [payload bytes][crc32 u32 of payload]
//   v2 "HTB2": [magic u32][dtype u32][ndim u32][shape u64 x ndim]
//              [raw u64][comp u64][payload comp bytes][crc32 u32 of RAW]
//   v2 adds zlib payload compression (comp == raw means stored raw; the
//   writer keeps whichever is smaller). The CRC always covers the RAW
//   bytes, so a bad inflate fails the same check as bit rot. Durable
//   commit to object stores is the reason this exists: the two-stage
//   protocol (ChkpManagerSlave.java:50-63 temp->HDFS) moves every block
//   over the network twice.
// ---------------------------------------------------------------------------

static const uint32_t BLK_MAGIC = 0x48544231u;   // "HTB1"
static const uint32_t BLK_MAGIC2 = 0x48544232u;  // "HTB2"
#define BLK_MAX_NDIM 8

// 0 on success, negative on error.
int32_t ht_blk_write(const char* path, const void* data, uint64_t nbytes,
                     const uint64_t* shape, int32_t ndim, int32_t dtype_code) {
  if (ndim < 0 || ndim > BLK_MAX_NDIM) return -2;
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  uint32_t head[3] = {BLK_MAGIC, (uint32_t)dtype_code, (uint32_t)ndim};
  uint32_t crc = ht_crc32((const uint8_t*)data, nbytes);
  int ok = fwrite(head, sizeof(head), 1, f) == 1 &&
           (ndim == 0 || fwrite(shape, sizeof(uint64_t), ndim, f) == (size_t)ndim) &&
           (nbytes == 0 || fwrite(data, 1, nbytes, f) == nbytes) &&
           fwrite(&crc, sizeof(crc), 1, f) == 1;
  ok = (fflush(f) == 0) && ok;
  ok = (fclose(f) == 0) && ok;
  return ok ? 0 : -3;
}

// v2 writer: zlib-compress the payload at `level` (1..9; <=0 stores raw).
// Keeps whichever of raw/compressed is smaller. 0 on success.
int32_t ht_blk_write2(const char* path, const void* data, uint64_t nbytes,
                      const uint64_t* shape, int32_t ndim, int32_t dtype_code,
                      int32_t level) {
  if (ndim < 0 || ndim > BLK_MAX_NDIM) return -2;
  uint8_t* comp_buf = nullptr;
  uint64_t comp_n = nbytes;  // == raw means "stored raw"
  if (level > 0 && nbytes > 0) {
    uLongf bound = compressBound((uLong)nbytes);
    comp_buf = (uint8_t*)malloc(bound);
    if (!comp_buf) return -7;
    uLongf got = bound;
    if (compress2(comp_buf, &got, (const Bytef*)data, (uLong)nbytes,
                  level > 9 ? 9 : level) == Z_OK &&
        (uint64_t)got < nbytes) {
      comp_n = (uint64_t)got;
    } else {
      free(comp_buf);
      comp_buf = nullptr;  // incompressible: store raw
    }
  }
  FILE* f = fopen(path, "wb");
  if (!f) {
    free(comp_buf);
    return -1;
  }
  uint32_t head[3] = {BLK_MAGIC2, (uint32_t)dtype_code, (uint32_t)ndim};
  uint64_t sizes[2] = {nbytes, comp_n};
  uint32_t crc = ht_crc32((const uint8_t*)data, nbytes);
  const void* payload = comp_buf ? (const void*)comp_buf : data;
  int ok = fwrite(head, sizeof(head), 1, f) == 1 &&
           (ndim == 0 || fwrite(shape, sizeof(uint64_t), ndim, f) == (size_t)ndim) &&
           fwrite(sizes, sizeof(uint64_t), 2, f) == 2 &&
           (comp_n == 0 || fwrite(payload, 1, comp_n, f) == comp_n) &&
           fwrite(&crc, sizeof(crc), 1, f) == 1;
  ok = (fflush(f) == 0) && ok;
  ok = (fclose(f) == 0) && ok;
  free(comp_buf);
  return ok ? 0 : -3;
}

// Phase 1 (out == NULL): fills *dtype_out, *ndim_out, shape_out and returns
// the RAW payload byte count. Phase 2 (out != NULL, out_cap >= nbytes):
// copies (v2: inflates) the payload, verifies the raw CRC. Returns nbytes
// on success; negative on error (-4 bad magic / truncated header,
// -5 payload/out_cap mismatch, -6 CRC mismatch — the corrupt-block signal,
// -7 OOM, -8 inflate failure).
int64_t ht_blk_read(const char* path, void* out, uint64_t out_cap,
                    uint64_t* shape_out, int32_t* ndim_out,
                    int32_t* dtype_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint32_t head[3];
  if (fread(head, sizeof(head), 1, f) != 1 ||
      (head[0] != BLK_MAGIC && head[0] != BLK_MAGIC2) ||
      head[2] > BLK_MAX_NDIM) {
    fclose(f);
    return -4;
  }
  int is_v2 = head[0] == BLK_MAGIC2;
  int32_t ndim = (int32_t)head[2];
  uint64_t shape[BLK_MAX_NDIM];
  if (ndim > 0 && fread(shape, sizeof(uint64_t), ndim, f) != (size_t)ndim) {
    fclose(f);
    return -4;
  }
  uint64_t raw_n = 0, comp_n = 0;
  if (is_v2) {
    uint64_t sizes[2];
    if (fread(sizes, sizeof(uint64_t), 2, f) != 2) { fclose(f); return -4; }
    raw_n = sizes[0];
    comp_n = sizes[1];
    // Sanity-bound the header-carried sizes BEFORE anyone allocates from
    // them: a bit flip in raw_n must fail like any other corruption, not
    // drive an unbounded allocation in the caller. zlib's worst-case
    // expansion is < 1032x (+ small constant); comp > raw never happens
    // (the writer stores raw in that case).
    if (comp_n > raw_n ||
        (comp_n != raw_n && raw_n > comp_n * 1032 + 1024)) {
      fclose(f);
      return -4;
    }
  }
  long data_start = ftell(f);
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return -4; }
  long file_end = ftell(f);
  int64_t stored = file_end - data_start - (long)sizeof(uint32_t);
  if (stored < 0) { fclose(f); return -4; }
  if (!is_v2) {
    raw_n = comp_n = (uint64_t)stored;
  } else if ((uint64_t)stored != comp_n) {
    fclose(f);
    return -4;  // truncated payload
  }
  if (dtype_out) *dtype_out = (int32_t)head[1];
  if (ndim_out) *ndim_out = ndim;
  if (shape_out)
    for (int32_t i = 0; i < ndim; i++) shape_out[i] = shape[i];
  if (!out) {  // metadata probe
    fclose(f);
    return (int64_t)raw_n;
  }
  if (raw_n > out_cap) { fclose(f); return -5; }
  if (fseek(f, data_start, SEEK_SET) != 0) { fclose(f); return -4; }
  int64_t rc = (int64_t)raw_n;
  if (comp_n == raw_n) {  // stored raw (v1, or incompressible v2)
    if (raw_n > 0 && fread(out, 1, (size_t)raw_n, f) != (size_t)raw_n) rc = -4;
  } else {
    uint8_t* comp_buf = (uint8_t*)malloc(comp_n ? comp_n : 1);
    if (!comp_buf) { fclose(f); return -7; }
    if (fread(comp_buf, 1, (size_t)comp_n, f) != (size_t)comp_n) {
      rc = -4;
    } else {
      uLongf got = (uLongf)raw_n;
      if (uncompress((Bytef*)out, &got, comp_buf, (uLong)comp_n) != Z_OK ||
          (uint64_t)got != raw_n)
        rc = -8;
    }
    free(comp_buf);
  }
  uint32_t crc_stored = 0;
  if (rc >= 0 && fread(&crc_stored, sizeof(crc_stored), 1, f) != 1) rc = -4;
  fclose(f);
  if (rc >= 0 &&
      ht_crc32((const uint8_t*)out, raw_n) != crc_stored)
    return -6;
  return rc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Prefetching split loader: an ordered background reader pool.
//
// The reference overlaps training with data arrival only at bulk-load time
// (HDFS client threads inside TableLoadMsg handling); here a C++ worker pool
// reads split byte-ranges ahead of the training loop with bounded lookahead,
// delivering splits IN ORDER so epoch composition stays deterministic.
// Record-boundary semantics replicate harmony_tpu/data/splits.py
// _fetch_range exactly (LineRecordReader alignment: a record belongs to the
// split containing its first byte; the last record is finished by reading
// past the range end) — parity is pinned by tests/test_native.py.
// ---------------------------------------------------------------------------

#include <condition_variable>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

int fetch_range_cc(const std::string& path, uint64_t offset, uint64_t length,
                   std::string& out) {
  if (length == 0) return 0;
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return -1;
  std::string chunk;
  if (offset > 0) {
    if (fseeko(f, (off_t)(offset - 1), SEEK_SET) != 0) { fclose(f); return -1; }
    chunk.resize(length + 1);
    size_t got = fread(&chunk[0], 1, length + 1, f);
    chunk.resize(got);
    size_t nl = chunk.find('\n');
    if (nl == std::string::npos) { fclose(f); return 0; }  // mid-record range
    chunk.erase(0, nl + 1);
    if (chunk.empty()) { fclose(f); return 0; }  // no record starts here
  } else {
    chunk.resize(length);
    size_t got = fread(&chunk[0], 1, length, f);
    chunk.resize(got);
  }
  if (chunk.empty() || chunk.back() != '\n') {
    char buf[4096];
    for (;;) {
      size_t got = fread(buf, 1, sizeof buf, f);
      if (!got) break;
      char* nl = (char*)memchr(buf, '\n', got);
      if (nl) { chunk.append(buf, nl - buf + 1); break; }
      chunk.append(buf, got);
    }
  }
  fclose(f);
  out += chunk;
  return 0;
}

struct Piece { std::string path; uint64_t offset, length; };

struct Prefetcher {
  std::vector<std::vector<Piece>> splits;   // per split: its pieces
  int32_t depth;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  size_t next_claim = 0;    // next split index a worker may take
  size_t next_deliver = 0;  // next split index ht_prefetch_next returns
  std::map<size_t, std::pair<std::string, int>> results;  // idx -> (bytes, err)
  bool closing = false;
  std::vector<std::thread> workers;

  void worker() {
    for (;;) {
      size_t idx;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] {
          return closing ||
                 (next_claim < splits.size() &&
                  next_claim < next_deliver + (size_t)depth);
        });
        if (closing) return;
        idx = next_claim++;
      }
      std::string bytes;
      int err = 0;
      for (const Piece& p : splits[idx]) {
        if (fetch_range_cc(p.path, p.offset, p.length, bytes) != 0) {
          err = -1;
          break;
        }
        // Terminate each piece's contribution: a file with no trailing
        // newline must not fuse its last record with the next piece's
        // first (the Python path splits per piece, so parity needs this).
        if (!bytes.empty() && bytes.back() != '\n') bytes.push_back('\n');
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        results[idx] = {std::move(bytes), err};
      }
      cv_done.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* ht_prefetch_open(const char* const* paths, const uint64_t* offsets,
                       const uint64_t* lengths, const int32_t* piece_counts,
                       int32_t n_splits, int32_t depth, int32_t n_workers) {
  if (n_splits < 0 || depth < 1 || n_workers < 1) return nullptr;
  Prefetcher* pf = new Prefetcher();
  pf->depth = depth;
  size_t k = 0;
  pf->splits.resize((size_t)n_splits);
  for (int32_t i = 0; i < n_splits; i++) {
    for (int32_t j = 0; j < piece_counts[i]; j++, k++) {
      pf->splits[i].push_back(Piece{paths[k], offsets[k], lengths[k]});
    }
  }
  int32_t nw = n_workers < n_splits ? n_workers : (n_splits ? n_splits : 1);
  for (int32_t i = 0; i < nw; i++)
    pf->workers.emplace_back([pf] { pf->worker(); });
  return pf;
}

// Returns the byte length of the next split (in submission order) and sets
// *out to a malloc'd buffer the caller frees with ht_prefetch_buf_free.
// -1 = all splits delivered; -2 = read error on this split.
int64_t ht_prefetch_next(void* h, uint8_t** out) {
  Prefetcher* pf = (Prefetcher*)h;
  std::string bytes;
  int err;
  {
    std::unique_lock<std::mutex> lk(pf->mu);
    if (pf->next_deliver >= pf->splits.size()) return -1;
    size_t idx = pf->next_deliver;
    pf->cv_done.wait(lk, [&] { return pf->results.count(idx) > 0; });
    auto it = pf->results.find(idx);
    bytes = std::move(it->second.first);
    err = it->second.second;
    pf->results.erase(it);
    pf->next_deliver++;
  }
  pf->cv_work.notify_all();  // lookahead window advanced
  if (err != 0) return -2;
  // One deliberate copy: the split's bytes move from the worker's string
  // into a C-owned buffer the caller frees; with bounded lookahead the
  // transient is depth x split-size, which the depth knob already caps.
  uint8_t* buf = (uint8_t*)malloc(bytes.size() ? bytes.size() : 1);
  if (!buf) return -3;  // OOM surfaces as an error, not a memcpy crash
  memcpy(buf, bytes.data(), bytes.size());
  *out = buf;
  return (int64_t)bytes.size();
}

void ht_prefetch_buf_free(uint8_t* p) { free(p); }

void ht_prefetch_close(void* h) {
  Prefetcher* pf = (Prefetcher*)h;
  {
    std::lock_guard<std::mutex> lk(pf->mu);
    pf->closing = true;
  }
  pf->cv_work.notify_all();
  for (std::thread& t : pf->workers) t.join();
  delete pf;
}

}  // extern "C"
