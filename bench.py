#!/usr/bin/env python
"""Headline benchmark — BASELINE.md config 4: aggregate training throughput
of CONCURRENT MLR + NMF + LDA jobs sharing one mesh under the JobServer
(the reference's north-star metric: aggregate samples/sec across concurrent
jobs on a shared multi-tenant substrate).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N}

The reference publishes no numbers (BASELINE.md: "published: {}"); its
north-star target is >=4x a CPU-cluster aggregate. ``vs_baseline`` is the
measured accelerator aggregate divided by the SAME three concurrent jobs run
on this host's CPU backend — the honest local proxy: >=4.0 meets the north
star. Both backends run a 1-epoch WARMUP pass first with a persistent XLA
compilation cache enabled, so the recorded rate is steady-state training
throughput (the north-star quantity) rather than a compile-time race —
see enable_compile_cache().
"""
import json
import os
import subprocess
import sys
import time

import jax

# Allow both the accelerator and CPU backends so the baseline runs in-process.
try:
    plats = jax.config.jax_platforms
    if plats and "cpu" not in plats:
        jax.config.update("jax_platforms", plats + ",cpu")
except Exception:
    pass

from harmony_tpu.config.params import JobConfig, TrainerParams  # noqa: E402
from harmony_tpu.utils.devices import discover_devices as _discover_devices  # noqa: E402
from harmony_tpu.jobserver.server import JobServer  # noqa: E402
from harmony_tpu.parallel.mesh import DevicePool  # noqa: E402

EPOCHS = 12
BATCHES = 8
METRIC = "aggregate throughput, concurrent MLR+NMF+LDA (multi-tenant jobserver)"


def job_configs(scale: float, epochs: int = EPOCHS):
    """The three BASELINE jobs, sized so per-sample compute lands on the
    MXU (large matmuls — MLR 8192x256, NMF rank-256); ``scale`` shrinks
    the CPU baseline run's DATASET only (per-sample compute is identical —
    rates, not totals, are compared)."""
    mlr_n = max(int(16384 * scale), BATCHES * 64)
    nmf_rows = max(int(4096 * scale), BATCHES * 8)
    lda_docs = max(int(2048 * scale), BATCHES * 8)
    mlr = JobConfig(
        job_id="bench-mlr", app_type="dolphin",
        trainer="harmony_tpu.apps.mlr:MLRTrainer",
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=BATCHES, comm_probe_period=6,
            app_params={"num_classes": 256, "num_features": 8192,
                        "features_per_partition": 512, "step_size": 0.05},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.mlr:make_synthetic",
              "data_args": {"n": mlr_n, "num_features": 8192,
                            "num_classes": 256}},
    )
    nmf = JobConfig(
        job_id="bench-nmf", app_type="dolphin",
        trainer="harmony_tpu.apps.nmf:NMFTrainer",
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=BATCHES, comm_probe_period=6,
            app_params={"num_rows": nmf_rows, "num_cols": 4096, "rank": 256,
                        "step_size": 0.01},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.nmf:make_synthetic",
              "data_args": {"num_rows": nmf_rows, "num_cols": 4096,
                            "rank": 256}},
    )
    lda = JobConfig(
        job_id="bench-lda", app_type="dolphin",
        trainer="harmony_tpu.apps.lda:LDATrainer",
        params=TrainerParams(
            num_epochs=epochs, num_mini_batches=BATCHES, comm_probe_period=6,
            app_params={"vocab_size": 8192, "num_topics": 64,
                        "num_docs": lda_docs, "max_doc_len": 128},
        ),
        num_workers=1,
        user={"data_fn": "harmony_tpu.apps.lda:make_synthetic",
              "data_args": {"num_docs": lda_docs, "vocab_size": 8192,
                            "num_topics": 64, "doc_len": 128}},
    )
    # examples processed per job = epochs * dataset size
    totals = {"bench-mlr": epochs * mlr_n, "bench-nmf": epochs * nmf_rows,
              "bench-lda": epochs * lda_docs}
    return [mlr, nmf, lda], totals


def enable_compile_cache() -> None:
    """Persistent XLA compilation cache: the WARMUP pass compiles each
    job's programs, the MEASURED pass hits the cache — so the recorded
    aggregate is steady-state throughput (the north-star quantity: these
    are long-running training jobs) on BOTH backends, not a compile-time
    race. Remote-attached chips compile over the tunnel (~20-40s/job),
    which otherwise dominates a minutes-long run."""
    import os

    # Fixed per-user dir (not a fresh mkdtemp): no /tmp litter per run, and
    # repeated bench invocations reuse each other's compiles.
    cache_dir = os.path.join(os.path.expanduser("~"), ".cache",
                             "harmony_tpu", "jit-cache")
    os.makedirs(cache_dir, exist_ok=True)
    for k, v in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(k, v)
        except Exception:  # older jax: cache simply stays off
            pass


def submit_and_time(server, configs, timeout_s: float):
    """Submit ``configs`` together; wait for all; returns {job_id:
    seconds-from-common-start}, stamped by done-callbacks so a job
    finishing before an earlier-submitted one gets ITS OWN completion
    time. Shared by bench.py and benchmarks/fairness.py."""
    job_walls: dict = {}
    t0 = time.perf_counter()

    def stamp(job_id):
        return lambda _f: job_walls.setdefault(
            job_id, round(time.perf_counter() - t0, 2))

    futures = []
    for c in configs:
        f = server.submit(c)
        f.add_done_callback(stamp(c.job_id))
        futures.append(f)
    for f in futures:
        f.result(timeout=timeout_s)
    return job_walls


def run_concurrent(devices, scale: float, job_timeout: float = 900.0,
                   epochs: int = EPOCHS) -> "tuple[float, dict]":
    """Submit the three jobs concurrently to one JobServer over ``devices``;
    returns (aggregate samples/sec = total examples / wall, per-job wall
    seconds). ``job_timeout`` bounds each job: tight for the accelerator
    pass (a wedged chip must surface as an error line, not a stall),
    looser for the slow-but-healthy CPU reference pass."""
    configs, totals = job_configs(scale, epochs)
    server = JobServer(num_executors=len(devices),
                       device_pool=DevicePool(devices))
    server.start()
    try:
        t0 = time.perf_counter()
        job_walls = submit_and_time(server, configs, job_timeout)
        wall = time.perf_counter() - t0
    finally:
        server.shutdown(timeout=120)
    total = sum(totals.values())
    rate = total / wall
    # per-job completion: the aggregate is bounded by the LAST job, so
    # the straggler app is the next perf target — make it visible
    print(f"  {len(configs)} jobs, {total} examples, {wall:.1f}s "
          f"-> {rate:,.0f} samples/sec aggregate; per-job {job_walls}",
          file=sys.stderr)
    from harmony_tpu.data import devcache
    from harmony_tpu.runtime import progcache
    print(f"  progcache {progcache.stats()}  devcache {devcache.stats()}",
          file=sys.stderr)
    return rate, job_walls


class ProbeError(RuntimeError):
    """Accelerator probe exhausted its attempts. Carries the structured
    per-attempt diagnostics so the BENCH json records WHAT happened each
    try instead of a bare 'unreachable' string (the probe wedged four
    rounds running with no trail)."""

    def __init__(self, attempts_log):
        self.attempts_log = list(attempts_log)
        last = attempts_log[-1]["error"] if attempts_log else "no attempts"
        super().__init__(
            f"{len(attempts_log)} probe attempt(s) failed; last: {last}")


def _kill_probe(proc) -> None:
    """Kill-on-timeout that cannot itself hang the bench: SIGKILL the
    probe's whole process group (it may have spawned plugin helpers),
    then give the reap a BOUNDED wait — a child stuck in uninterruptible
    IO (the wedged-transport failure mode that motivated subprocess
    probes) is abandoned to init rather than blocking this run."""
    import os as _os
    import signal as _signal

    try:
        _os.killpg(proc.pid, _signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass
    try:
        proc.communicate(timeout=10)
    except (subprocess.TimeoutExpired, OSError, ValueError):
        pass  # unreaped zombie or D-state child: abandoned, not waited on


def probe_accelerator(attempts: int = 3,
                      timeout_s: float = 60.0) -> "tuple[str, list]":
    """Probe accelerator health in a SUBPROCESS, retrying with backoff.

    In-process retries can't help once a wedged transport has blocked a
    backend-init thread (later attempts pile onto the same init lock), so
    each attempt is a fresh interpreter IN ITS OWN PROCESS GROUP with a
    kill-on-timeout bound (_kill_probe). Returns (platform, attempts_log)
    on success; raises :class:`ProbeError` carrying the per-attempt
    diagnostics on final failure."""
    code = "import jax; ds = jax.devices(); print('PROBE', ds[0].platform, len(ds))"
    log: list = []
    for i in range(attempts):
        if i:
            backoff = 5.0 * i
            print(f"  discovery retry {i + 1}/{attempts} in {backoff:.0f}s",
                  file=sys.stderr)
            time.sleep(backoff)
        rec = {"attempt": i + 1, "timeout_s": timeout_s}
        t0 = time.monotonic()
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            start_new_session=True,  # own process group: killable whole
        )
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            _kill_probe(proc)
            rec.update(outcome="timeout",
                       seconds=round(time.monotonic() - t0, 1),
                       error=f"probe hung >{timeout_s:.0f}s (killed)")
            log.append(rec)
            continue
        rec["seconds"] = round(time.monotonic() - t0, 1)
        for line in out.splitlines():
            if line.startswith("PROBE "):
                _, platform, count = line.split()
                print(f"  probe: {count} {platform} device(s)",
                      file=sys.stderr)
                rec.update(outcome="ok", platform=platform,
                           devices=int(count))
                log.append(rec)
                return platform, log
        rec.update(outcome="error", rc=proc.returncode,
                   error=f"rc={proc.returncode}, "
                         f"stderr tail: {err[-300:]!r}")
        log.append(rec)
    raise ProbeError(log)


def cpu_baseline_rate() -> float:
    """Best of two measured CPU passes (after a compile warmup).

    A single pass proved fragile: transient host contention (another
    process hammering the tunnel/cores) once depressed it 5x, which
    INFLATES vs_baseline. Taking the best CPU rate is the conservative
    denominator — steady-state capability of this host, not its worst
    moment."""
    try:
        cpu = jax.devices("cpu")[:1]
        print("cpu warmup (compile) pass:", file=sys.stderr)
        run_concurrent(cpu, scale=0.125, job_timeout=3600.0, epochs=1)
        rates = []
        for i in range(2):
            print(f"concurrent MLR+NMF+LDA on cpu (reduced size, "
                  f"pass {i + 1}/2):", file=sys.stderr)
            rates.append(run_concurrent(cpu, scale=0.125,
                                        job_timeout=3600.0)[0])
        return max(rates)
    except Exception as e:  # pragma: no cover - cpu backend always present
        print(f"cpu baseline unavailable: {e}", file=sys.stderr)
        return 0.0


def measure_scrape_latency() -> "dict | None":
    """Exporter-overhead probe (tracked round over round in BENCH json):
    serve the process registry — populated by the training passes that
    just ran — on an ephemeral port and time a few real HTTP scrapes.
    Returns {metrics_scrape_ms, scrape_bytes, families} or None when the
    probe itself fails (the bench line must never die for its
    observability hook)."""
    import urllib.request

    try:
        from harmony_tpu.metrics.exporter import MetricsExporter
        from harmony_tpu.metrics.registry import parse_exposition

        exp = MetricsExporter(0).start()
        try:
            samples = []
            body = b""
            for _ in range(5):
                t0 = time.perf_counter()
                body = urllib.request.urlopen(exp.url + "/metrics",
                                              timeout=10).read()
                samples.append((time.perf_counter() - t0) * 1000.0)
            return {
                "metrics_scrape_ms": round(sorted(samples)[len(samples) // 2], 3),
                "scrape_bytes": len(body),
                "families": len(parse_exposition(body.decode())),
            }
        finally:
            exp.stop()
    except Exception:
        return None


def measure_state_movement() -> "dict | None":
    """State-movement latency probe (tracked round over round in BENCH
    json beside throughput): a small checkpoint restore and a small TCP
    block-migration exchange, both on the CPU backend so every round is
    comparable regardless of accelerator health. Returns
    {"chkp.restore_ms", "move.exchange_ms", ...} or None — the bench
    line must never die for its state-movement hook."""
    import shutil
    import tempfile

    import numpy as np

    root = tempfile.mkdtemp(prefix="harmony-bench-sm-")
    try:
        from harmony_tpu.checkpoint import CheckpointManager
        from harmony_tpu.config.params import TableConfig
        from harmony_tpu.parallel import DevicePool
        from harmony_tpu.runtime import ETMaster
        from harmony_tpu.table import blockmove

        cpu = jax.devices("cpu")
        master = ETMaster(DevicePool(cpu[:1]))
        execs = [e.id for e in master.add_executors(1)]
        nb, rows, dim = 32, 256, 256  # 32 x 256 KB = 8 MB
        cfg = TableConfig(table_id="bench-sm", capacity=nb * rows,
                          value_shape=(dim,), num_blocks=nb)
        h = master.create_table(cfg, execs)
        vals = np.ones((nb * rows, dim), np.float32)
        h.table.multi_update(list(range(nb * rows)), vals)
        mgr = CheckpointManager(root + "/temp", root + "/commit")
        cid = mgr.checkpoint(h)
        samples = []
        for i in range(3):
            t0 = time.perf_counter()
            rh = mgr.restore(master, cid, execs, table_id=f"bench-sm-r{i}")
            samples.append((time.perf_counter() - t0) * 1000.0)
            rh.drop()
        restore_ms = sorted(samples)[len(samples) // 2]

        class _KV:
            def __init__(self):
                self.kv = {}

            def key_value_set(self, k, v):
                self.kv[k] = v

            def blocking_key_value_get(self, k, timeout_ms):
                return self.kv[k]

            def key_value_delete(self, k):
                self.kv.pop(k, None)

        block = np.ones((rows, dim), np.float32)
        plan = blockmove.MovePlan(
            sends={0: [(b, 0) for b in range(nb)]},
            recvs={0: set(range(nb))}, block_nbytes=block.nbytes)
        outgoing = {b: block for b in range(nb)}
        orig_kv = blockmove._kv_client
        blockmove._kv_client = lambda: _KV()
        try:
            samples = []
            for i in range(3):
                t0 = time.perf_counter()
                received, _ = blockmove._tcp_exchange(plan, outgoing,
                                                      900000 + i)
                samples.append((time.perf_counter() - t0) * 1000.0)
                assert len(received) == nb
        finally:
            blockmove._kv_client = orig_kv
        exchange_ms = sorted(samples)[len(samples) // 2]
        from harmony_tpu.checkpoint.manager import _chkp_io_threads

        return {
            "chkp.restore_ms": round(restore_ms, 1),
            "move.exchange_ms": round(exchange_ms, 1),
            "chkp_mb": round(nb * rows * dim * 4 / 1e6, 1),
            "move_parallel": blockmove._move_parallel(),
            "io_threads": _chkp_io_threads(),
        }
    except Exception:
        return None
    finally:
        shutil.rmtree(root, ignore_errors=True)


def measure_sparse_hot_path() -> "dict | None":
    """Sparse device-hot-path probe (tracked round over round in BENCH
    json): a small embedding-SGD table driven fused (FusedSparseStep,
    one donated-buffer program per batch) and unfused (ModelAccessor
    host round trip), interleaved, on the CPU backend. Returns fused/
    unfused samples-per-sec, the ratio, the unfused arm's measured
    per-phase pull/comp/push seconds, and asserts loss parity — or None
    (the bench line must never die for its sparse-path hook). Full A/B:
    benchmarks/sparse_step_bench.py (SPARSE_STEP_r07.json)."""
    try:
        import jax.numpy as jnp
        import numpy as np

        from harmony_tpu.config.params import TableConfig
        from harmony_tpu.dolphin import ModelAccessor
        from harmony_tpu.parallel import build_mesh
        from harmony_tpu.table import DenseTable, TableSpec

        mesh = build_mesh(jax.devices("cpu")[:1])
        rows, width, batch, nb = 2048, 32, 256, 30
        rng = np.random.default_rng(0)
        batches = [
            (rng.integers(0, rows, batch).astype(np.int32),
             rng.normal(size=(batch, width)).astype(np.float32))
            for _ in range(nb)
        ]

        def table():
            return DenseTable(
                TableSpec(TableConfig(table_id="bench-sparse",
                                      capacity=rows, value_shape=(width,),
                                      num_blocks=32)), mesh)

        def compute(r, t):
            err = r - t
            return -0.05 * err, {"loss": jnp.mean(jnp.sum(err * err, -1))}

        acc_f = ModelAccessor(table())
        fs = acc_f.fused_step(compute, signature=("bench-sparse-hook",))
        fs.run_batches(batches[:2])  # compile warmup
        t0 = time.perf_counter()
        l_f = [float(a["loss"]) for a in fs.run_batches(batches)]
        fused_s = time.perf_counter() - t0

        acc = ModelAccessor(table())
        comp = jax.jit(compute)

        def one(keys, tgt):
            rows_h = acc.pull(keys)
            delta, aux = jax.block_until_ready(
                comp(jnp.asarray(rows_h), jnp.asarray(tgt)))
            acc.push(keys, np.asarray(delta))
            return float(aux["loss"])

        for k, t in batches[:2]:
            one(k, t)
        acc.get_and_reset_times()
        t0 = time.perf_counter()
        l_u = [one(k, t) for k, t in batches]
        unfused_s = time.perf_counter() - t0
        pull_s, push_s = acc.get_and_reset_times()
        if l_f != l_u:
            return {"error": "fused/unfused loss parity broke"}
        n = nb * batch
        return {
            "fused_sps": round(n / fused_s, 1),
            "unfused_sps": round(n / unfused_s, 1),
            "ratio": round(unfused_s / fused_s, 2),
            "unfused_pull_ms": round(pull_s * 1000, 2),
            "unfused_push_ms": round(push_s * 1000, 2),
            "unfused_comp_ms": round(
                max(unfused_s - pull_s - push_s, 0.0) * 1000, 2),
            "loss_parity": "bit-identical",
        }
    except Exception:
        return None


def measure_async_step() -> "dict | None":
    """Bounded-staleness async step probe (tracked round over round in
    the BENCH json, and by --compare via the dotted async_step.* series):
    a small MLR WorkerTasklet under an injected worker.pull delay, sync
    unfused vs async bound 0 (the bit-identical control) vs async bound
    1 (the overlap arm). Returns {sync_sps, b0_sps, b1_sps, speedup_b1,
    max_lag_b1, parity}, {"error": ...} on a parity break, or None — the
    bench line must never die for its async-step hook (pinned capture:
    benchmarks/ASYNC_STEP_r16.json)."""
    try:
        import os
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmarks.async_step_bench import run_arm

        # comp ~ delay is the regime where overlap shows: either side
        # dominating caps the win at the smaller of the two
        # full-bench shape (comp ~ delay ~ 4ms), fewer epochs
        probe = dict(epochs=2, batches=8)
        # two interleaved rounds, best-of per arm: round 1 pays the
        # compile (the progcache is warm from round 2 on), so a single
        # cold pass would mis-rank the arms
        sync_sps = b0_sps = b1_sps = 0.0
        b1_stats = {}
        for _ in range(2):
            sps, sync_losses, _ = run_arm(False, 0, **probe)
            sync_sps = max(sync_sps, sps)
            sps, b0_losses, _ = run_arm(True, 0, **probe)
            b0_sps = max(b0_sps, sps)
            if b0_losses != sync_losses:
                return {"error": "staleness-0 loss parity broke"}
            sps, _, st = run_arm(True, 1, **probe)
            if sps > b1_sps:
                b1_sps, b1_stats = sps, st
        return {
            "sync_sps": round(sync_sps, 1),
            "b0_sps": round(b0_sps, 1),
            "b1_sps": round(b1_sps, 1),
            "speedup_b1": round(b1_sps / sync_sps, 2),
            "max_lag_b1": b1_stats.get("max_lag", 0),
            "parity": "bit-identical",
        }
    except Exception:
        return None


def emit(tpu_rate: float, cpu_rate: float, error: str | None = None,
         job_walls: dict | None = None, probe_log: list | None = None) -> None:
    if error:
        # Accelerator unreachable/failed: the CPU measurement IS the run's
        # primary result. A "value": 0.0 / "vs_baseline": 0.0 line polluted
        # the perf trajectory (readers plotting `value` saw throughput
        # collapse to zero whenever the transport wedged); the explicit
        # "accelerator": "unreachable" field carries that state instead.
        line = {
            "metric": METRIC,
            "value": round(cpu_rate, 1),
            "unit": "samples/sec",
            "accelerator": "unreachable",
            "cpu_rate": round(cpu_rate, 1),
            "mode": "cpu fallback: 3 concurrent jobs, num_workers=1 each; "
                    "steady-state (compile warmed); accelerator pass did "
                    "not run",
        }
    else:
        line = {
            "metric": METRIC,
            "value": round(tpu_rate, 1),
            "unit": "samples/sec",
            "vs_baseline": round(tpu_rate / cpu_rate if cpu_rate > 0 else 0.0, 2),
            "cpu_rate": round(cpu_rate, 1),
            "mode": "3 concurrent jobs, num_workers=1 each (single chip); "
                    "steady-state (compile warmed on both backends)",
        }
    if job_walls:
        # the aggregate is bounded by the LAST job: the straggler app
        # named here is the next perf target
        line["accel_job_walls_s"] = job_walls
    if probe_log:
        # per-attempt probe diagnostics: what each bounded attempt saw
        # (outcome/rc/stderr tail/seconds) — readers of an unreachable
        # round get the trail, not a bare string
        line["probe"] = {
            "attempts": len(probe_log),
            "last_error": next(
                (r.get("error") for r in reversed(probe_log)
                 if r.get("error")), None),
            "per_attempt": probe_log,
        }
    if error:
        line["error"] = error
        # Provenance for readers of an error line: the most recent committed
        # HEALTHY on-chip capture of this same metric, if one exists (the
        # transport to the remote chip wedges for hours at a time; a capture
        # from a healthy window is the best available accelerator evidence).
        import glob
        import os

        pattern = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "BENCH_*_chip.json")
        for prior in sorted(glob.glob(pattern), reverse=True):
            try:
                with open(prior) as f:
                    data = json.load(f)
            except Exception:
                continue
            # only a clean capture of THIS metric counts as evidence —
            # never a crashed-stage stub or a nested error line
            if data.get("metric") == METRIC and "error" not in data:
                prior_line = dict(data, source=os.path.basename(prior))
                # honesty note rides WITH the stale capture: its embedded
                # vs_baseline used that session's (depressed) CPU rate
                # (ROUNDLOG round-2/4); recompute against THIS session's
                # measured denominator so no reader takes 8.75x at face
                # value
                if cpu_rate > 0 and data.get("value"):
                    prior_line["vs_this_sessions_cpu_rate"] = round(
                        data["value"] / cpu_rate, 2)
                    prior_line["note"] = (
                        "embedded vs_baseline used the capture session's "
                        "own CPU denominator, later found depressed; "
                        "vs_this_sessions_cpu_rate is the honest multiple "
                        "against today's measured CPU rate")
                line["prior_chip_capture"] = prior_line
                break
    obs = measure_scrape_latency()
    if obs is not None:
        # exporter overhead for THIS round's (training-populated)
        # registry — a /metrics endpoint that drifts slow shows up here
        line["obs"] = obs
    sm = measure_state_movement()
    if sm is not None:
        # state-movement latency (checkpoint restore + migration
        # exchange) tracked beside throughput, so future PRs see
        # recovery-path regressions in the same trajectory
        line["state_movement"] = sm
    sp = measure_sparse_hot_path()
    if sp is not None:
        # fused-vs-unfused sparse step throughput + the unfused arm's
        # measured per-phase pull/comp/push split, tracked round over
        # round so device-hot-path regressions land in the trajectory
        line["sparse_hot_path"] = sp
    asp = measure_async_step()
    if asp is not None:
        # bounded-staleness async step A/B (sync vs bound 0 control vs
        # bound 1 overlap) under an injected comm delay — --compare
        # holds async_step.b1_sps so an overlap regression fails
        # bin/bench_diff.sh (pinned capture: ASYNC_STEP_r16.json)
        line["async_step"] = asp
    isvc = measure_input_service()
    if isvc is not None:
        # disaggregated-input-service throughput A/B (small unpinned
        # probe; the committed INPUT_SVC_r*.json holds the pinned-budget
        # capture) — tracked so service-path regressions land in the
        # trajectory, and --compare checks input_service.svc_sps
        line["input_service"] = isvc
    lint = measure_lint()
    if lint is not None:
        # harmonylint suite runtime + finding counts: the suite runs in
        # tier-1 every round, so its wall time drifting up is a tax on
        # every CI pass — keep it visible in the same trajectory
        line["lint"] = lint
    od = measure_obs_doctor()
    if od is not None:
        # telemetry-history ingest + full-rule-evaluation wall time per
        # scrape cycle: the scraper/doctor run inside the jobserver at
        # HARMONY_OBS_SCRAPE_PERIOD cadence, so their overhead must be
        # measured, not assumed (pinned capture: OBS_DOCTOR_r11.json)
        line["obs_doctor"] = od
    ha = measure_ha()
    if ha is not None:
        # control-plane HA costs: per-transition durable-append (fsync)
        # overhead and standby takeover latency (election + fenced
        # replay) — both must stay flat as the control plane grows
        line["ha"] = ha
    cp = measure_critpath()
    if cp is not None:
        # step-phase budget computation + critical-path analysis wall
        # time: the snapshot runs on every ledger query / scrape cycle
        # and the analyzer on every STATUS, so their overhead rides the
        # trajectory too (pinned sweep: CRITPATH_r13.json)
        line["critpath"] = cp
    pol = measure_policy()
    if pol is not None:
        # device-policy plan-evaluation cost: the engine runs inside
        # the jobserver at HARMONY_POLICY_PERIOD cadence, so its
        # per-window overhead (and how many actions a loaded window
        # plans) must be measured, not assumed (docs/SCHEDULING.md)
        line["policy"] = pol
    asc = measure_autoscale()
    if asc is not None:
        # the closed loop itself: a 1-round churning-mix A/B (policy
        # off vs act) — --compare holds autoscale.agg_sps and
        # autoscale.slo_attainment so a regression in the loop fails
        # bin/bench_diff.sh (pinned capture: AUTOSCALE_r15.json)
        line["autoscale"] = asc
    cho = measure_chaos()
    if cho is not None:
        # seeded chaos smoke: two fast multi-fault scenarios through
        # the orchestrator; scenarios_ok dropping below scenarios_run
        # means an invariant went red on a pinned schedule (the full
        # sweep is benchmarks/CHAOS_r18.json, run by bin/chaos.sh)
        line["chaos"] = cho
    srv = measure_serving()
    if srv is not None:
        # online-serving probe: a short closed-loop read storm against a
        # live table through the micro-batching endpoint; --compare holds
        # serving.qps (higher=better) and serving.p99_ms (LOWER=better)
        # so a latency regression in the read path fails
        # bin/bench_diff.sh (pinned A/B grid: benchmarks/SERVING_r20.json)
        line["serving"] = srv
    oin = measure_obs_incidents()
    if oin is not None:
        # incident-correlation probe: a synthetic fault→diagnosis→
        # action→resolution stream through a standalone engine;
        # obs_incidents.recall dropping below 1.0 means seeded episodes
        # stopped correlating (the chaos-scored capture is
        # benchmarks/OBS_INCIDENT_r19.json)
        line["obs_incidents"] = oin
    print(json.dumps(line))


def measure_input_service() -> "dict | None":
    """Input-service probe (tracked round over round in the BENCH json,
    and by --compare via the dotted input_service.* series): a small
    multi-tenant-process service-vs-in-process A/B — 3 same-dataset
    tenant processes, standalone service, unpinned cores (the full
    pinned-budget capture is benchmarks/INPUT_SVC_r10.json). Returns
    {svc_sps, inproc_sps, speedup, parity} or None — the bench line
    must never die for its input-service hook."""
    try:
        import os
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmarks.bench_input_pipeline import run_service_bench

        r = run_service_bench(tenants=3, n=262144, epochs=2, rounds=1,
                              cores=0)
        if not r.get("losses_bit_identical"):
            return {"error": "service/in-process loss parity broke"}
        return {
            "svc_sps": r["service_sps"],
            "inproc_sps": r["inproc_sps"],
            "speedup": r["speedup"],
            "parity": "bit-identical",
        }
    except Exception:
        return None


def measure_obs_doctor() -> "dict | None":
    """Telemetry-history + doctor overhead probe (tracked round over
    round in the BENCH json): ingest of this process's REAL exposition
    (populated by the training passes that just ran) per scrape cycle,
    and one full rule evaluation over a store holding scenario-shaped
    tenant series. Returns {ingest_ms, diagnose_ms, series, points,
    rules, diagnoses} or None — the bench line must never die for its
    observability hook. Full sweep: benchmarks/obs_doctor.py
    (OBS_DOCTOR_r11.json)."""
    try:
        from harmony_tpu.metrics.doctor import Doctor, all_rules
        from harmony_tpu.metrics.history import HistoryStore
        from harmony_tpu.metrics.registry import get_registry

        text = get_registry().expose()
        store = HistoryStore(window_sec=900.0, resolution_sec=1.0)
        rounds = 20
        now = time.time()
        t0 = time.perf_counter()
        for i in range(rounds):
            store.ingest_exposition("leader", text,
                                    ts=now - (rounds - i))
        ingest_ms = (time.perf_counter() - t0) * 1000.0 / rounds
        # scenario-shaped tenant series so every rule has real work
        for j in range(8):
            labels = {"job": f"bench-t{j}", "attempt": f"bench-t{j}"}
            for i in range(30):
                ts = now - 30 + i
                store.ingest("tenant.input_wait_frac", labels,
                             0.8 if j % 2 else 0.1, ts=ts)
                store.ingest("tenant.straggler_ratio", labels,
                             2.5 if j % 3 == 0 else 1.0, ts=ts)
                store.ingest("tenant.mfu", labels,
                             0.4 if i < 15 else 0.1, ts=ts)
        doc = Doctor(store, events_fn=dict)
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            doc.diagnose()  # dedupe suppresses re-EMISSION, not the work
            samples.append((time.perf_counter() - t0) * 1000.0)
        st = store.stats()
        return {
            "ingest_ms": round(ingest_ms, 3),
            "diagnose_ms": round(sorted(samples)[len(samples) // 2], 3),
            "series": st["series"],
            "points": st["points"],
            "rules": len(all_rules()),
            "diagnoses": len(doc.recent()),
            "scrape_bytes": len(text),
        }
    except Exception:
        return None


def measure_critpath() -> "dict | None":
    """Step-phase budget + critical-path overhead probe (tracked round
    over round in the BENCH json): windowed budget computation
    (PhaseBudgetStore.snapshot — runs on every ledger query and scrape
    cycle) and the full critical-path analysis (critpath.analyze —
    runs on every STATUS) over a scenario-shaped store. Returns
    {budget_ms, analyze_ms, tenants, workers, epochs} or None — the
    bench line must never die for its observability hook. Full sweep:
    benchmarks/critpath.py (CRITPATH_r13.json)."""
    try:
        from harmony_tpu.metrics import critpath
        from harmony_tpu.metrics.phases import PhaseBudgetStore

        store = PhaseBudgetStore()
        tenants, workers, epochs = 8, 4, 24
        for j in range(tenants):
            for e in range(epochs):
                for w in range(workers):
                    store.observe_epoch(
                        f"bench-t{j}", f"bench-t{j}", f"w{w}", e,
                        0.1 + 0.01 * w,
                        {"input_wait": 0.01, "host_dispatch": 0.005,
                         "pull_comm": 0.01, "compute": 0.06,
                         "push_comm": 0.005})
        budget_samples = []
        for _ in range(10):
            t0 = time.perf_counter()
            snap = store.snapshot()
            budget_samples.append((time.perf_counter() - t0) * 1000.0)
        analyze_samples = []
        for _ in range(10):
            t0 = time.perf_counter()
            critpath.analyze(snap)
            analyze_samples.append((time.perf_counter() - t0) * 1000.0)
        return {
            "budget_ms": round(sorted(budget_samples)[5], 3),
            "analyze_ms": round(sorted(analyze_samples)[5], 3),
            "tenants": tenants, "workers": workers, "epochs": epochs,
        }
    except Exception:
        return None


def measure_ha() -> "dict | None":
    """Control-plane HA overhead probe (tracked round over round in
    the BENCH json): durable log-append cost (write+flush+fsync per
    control-plane transition — the tax every submission/dispatch/
    completion now pays on an HA leader) and warm-standby takeover
    latency (lease election + fenced replay + re-arm bookkeeping over
    a populated log; the server-boot share is excluded — it is the
    same cost a cold start pays). Returns {append_ms, appends_per_sec,
    takeover_ms, replayed_entries} or None — the bench line must never
    die for its HA hook."""
    try:
        import tempfile

        from harmony_tpu.jobserver.halog import DurableJobLog, ReplayState
        from harmony_tpu.jobserver.lease import LeaseManager

        root = tempfile.mkdtemp(prefix="harmony-bench-ha-")
        path = os.path.join(root, "job.walog")
        log = DurableJobLog(path)
        n = 256
        t0 = time.perf_counter()
        for i in range(n):
            kind = ("submission", "dispatch", "job_done")[i % 3]
            log.append(kind, job_id=f"bench-j{i % 8}",
                       config={"job_id": f"bench-j{i % 8}", "k": i})
        wall = time.perf_counter() - t0
        log.close()
        # takeover: election + reopen (torn-tail scan) + fenced replay
        samples = []
        replayed = 0
        for r in range(5):
            lease = LeaseManager(root, f"bench-rep-{r}", lease_s=30.0)
            t0 = time.perf_counter()
            if not lease.try_acquire():  # never assert: -O strips it,
                raise RuntimeError("bench lease acquire failed")
            relog = DurableJobLog(path)
            relog.set_epoch(lease.epoch)
            st = ReplayState.from_entries(relog.entries())
            samples.append((time.perf_counter() - t0) * 1000.0)
            replayed = st.entries_applied
            relog.close()
            lease.release()
        import shutil

        shutil.rmtree(root, ignore_errors=True)
        return {
            "append_ms": round(wall * 1000.0 / n, 4),
            "appends_per_sec": round(n / wall, 1),
            "takeover_ms": round(sorted(samples)[len(samples) // 2], 3),
            "replayed_entries": replayed,
        }
    except Exception:
        return None


def measure_policy() -> "dict | None":
    """Device-policy engine overhead probe (tracked round over round in
    the BENCH json): full plan evaluations over a synthetic 16-tenant
    contention window (queued claimant + growable/packable tenants) in
    ``act`` mode against a null fence. Returns {eval_ms, tenants,
    actions_planned, actions_per_window} or None — the bench line must
    never die for its policy hook."""
    try:
        from harmony_tpu.jobserver.policy import ActionGate, PolicyEngine

        n = 16
        rows = {}
        tenants = {}
        for i in range(n):
            jid = f"bench-pol-{i:02d}"
            rows[jid] = {
                "slo": {"attainment": 0.4 if i % 3 == 0 else 1.0},
                "phase_class": ("compute-bound" if i % 3 == 0
                                else "dispatch-bound" if i % 3 == 1
                                else "balanced"),
                "input_wait_frac": 0.1, "mfu": None,
                "samples_per_sec": 1000.0 + i,
            }
            tenants[jid] = {"executors": [f"e{2 * i}", f"e{2 * i + 1}"],
                            "attempt": 0, "priority": i % 2}

        class _Sched:
            def idle_executors(self):
                return ["idle0"]

            def queued_jobs(self):
                return []

            def plan_grant(self, job_id, executors, shared=False):
                pass

        import os as _os

        saved = _os.environ.get("HARMONY_POLICY")
        _os.environ["HARMONY_POLICY"] = "act"
        try:
            eng = PolicyEngine(
                scheduler=_Sched(), ledger_fn=lambda: rows,
                tenants_fn=lambda: tenants,
                fence_fn=lambda j, k: None,  # plans, never lands
                gate=ActionGate(cooldown_sec=0.0, confirm=1,
                                stale_after=999.0))
            samples = []
            planned = 0
            for _ in range(20):
                t0 = time.perf_counter()
                plan = eng.evaluate()
                samples.append((time.perf_counter() - t0) * 1000.0)
                planned = len(plan["actions"])
        finally:
            if saved is None:
                _os.environ.pop("HARMONY_POLICY", None)
            else:
                _os.environ["HARMONY_POLICY"] = saved
        return {
            "eval_ms": round(sorted(samples)[len(samples) // 2], 3),
            "tenants": n,
            "actions_per_window": planned,
        }
    except Exception:
        return None


def measure_autoscale() -> "dict | None":
    """Closed-loop autoscaling probe (tracked round over round in the
    BENCH json, and by --compare via the dotted autoscale.* series): a
    1-round policy-off-vs-act churning-mix A/B (the full interleaved
    capture is benchmarks/AUTOSCALE_r15.json). Returns {agg_sps,
    slo_attainment, agg_speedup, attainment_gain,
    time_to_rebalance_sec, parity} or None — the bench line must never
    die for its autoscale hook."""
    try:
        import os as _os
        import sys as _sys

        _sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
        from benchmarks.autoscale import run_autoscale

        r = run_autoscale(rounds=1)
        if not r.get("loss_parity"):
            return {"error": "policy-on/off loss parity broke"}
        return {
            "agg_sps": r["agg_sps"],
            "slo_attainment": r["slo_attainment"],
            "agg_speedup": r["agg_speedup"],
            "attainment_gain": r["attainment_gain"],
            "time_to_rebalance_sec": r["time_to_rebalance_sec"],
            "parity": "exact",
        }
    except Exception:
        return None


def measure_chaos() -> "dict | None":
    """Seeded chaos smoke probe (tracked round over round in the BENCH
    json, and by --compare via chaos.scenarios_ok): a fixed pair of
    fast seeded scenarios — the ENOSPC-mid-commit checkpoint schedule
    and the halog-ENOSPC submission schedule — through the real
    orchestrator with the whole-system invariant checker as the
    verdict (the full sweep is benchmarks/CHAOS_r18.json; bin/chaos.sh
    runs it). Returns {scenarios_run, scenarios_ok,
    invariant_violations, wall_s} or None — the bench line must never
    die for its chaos hook."""
    try:
        from harmony_tpu.faults.chaos import run_scenario

        runs = [run_scenario(5, intensity=0.6,
                             scenario="chkp_enospc_commit"),
                run_scenario(11, intensity=0.5,
                             scenario="halog_enospc")]
        violations = sorted({v for r in runs for v in r["violations"]})
        return {
            "scenarios_run": len(runs),
            "scenarios_ok": sum(1 for r in runs if r["ok"]),
            "invariant_violations": violations,
            "wall_s": round(sum(r["wall_s"] for r in runs), 2),
        }
    except Exception:
        return None


def measure_obs_incidents() -> "dict | None":
    """Incident-correlation probe (tracked round over round in the
    BENCH json, and by --compare via obs_incidents.recall): a fixed
    synthetic episode set — 8 tenants, each a seeded trigger→diagnosis→
    action→resolution joblog sequence — through a standalone
    IncidentEngine, measuring correlation wall per cycle, the open
    count after folding, and recall (episodes that produced a resolved
    incident / episodes injected). Synthetic on purpose: the BENCH line
    must stay cheap; the chaos-ground-truth scorecard is
    benchmarks/OBS_INCIDENT_r19.json (benchmarks/obs_incidents.py).
    Returns {correlate_ms, open, recall, resolved} or None — the bench
    line must never die for its incidents hook."""
    try:
        import time as _t

        from harmony_tpu.jobserver import joblog
        from harmony_tpu.metrics.incidents import IncidentEngine

        n = 8
        eng = IncidentEngine(window_sec=5.0, persist=False)
        t0 = _t.time()
        for i in range(n):
            job = f"bench-inc-{i}"
            joblog.record_event(job, "slo", attainment=0.4)
            joblog.record_event(job, "diagnosis", rule="slo_burn",
                                verdict="input_bound", confidence=0.9)
            joblog.record_event(job, "policy", action="grow",
                                outcome="advised", reason="under_slo")
            joblog.record_event(job, "elastic_restore", recovery="regrow")
        t1 = _t.monotonic()
        eng.correlate()
        correlate_ms = (_t.monotonic() - t1) * 1000.0
        st = eng.status()
        for i in range(n):
            joblog.clear_events(f"bench-inc-{i}")
        return {
            "correlate_ms": round(correlate_ms, 3),
            "open": st["open"],
            "resolved": st["resolved"],
            "recall": round(st["resolved"] / float(n), 3),
            "setup_s": round(_t.time() - t0, 3),
        }
    except Exception:
        return None


def measure_serving() -> "dict | None":
    """Online-serving probe (tracked round over round in the BENCH json,
    and by --compare via serving.qps / serving.p99_ms): a short
    closed-loop read storm — 4 client threads, skewed keys — against a
    small live DenseTable through the micro-batching ServingEndpoint
    (batch window + hot-row cache on, the production defaults).
    Returns {qps, p50_ms, p99_ms, cache_hit_rate, batch_occupancy} or
    None — the bench line must never die for its serving hook. The
    pinned batching×cache×training A/B grid is
    benchmarks/SERVING_r20.json (benchmarks/serving_bench.py)."""
    try:
        import threading as _th

        import numpy as np

        from harmony_tpu.config.params import TableConfig
        from harmony_tpu.parallel import build_mesh
        from harmony_tpu.serving import ServingEndpoint
        from harmony_tpu.serving import protocol as _sp
        from harmony_tpu.table import DenseTable, TableSpec

        mesh = build_mesh(jax.devices("cpu")[:1])
        cap, width = 1024, 32
        table = DenseTable(
            TableSpec(TableConfig(table_id="bench-serve", capacity=cap,
                                  value_shape=(width,), num_blocks=8)),
            mesh)
        table.multi_put(np.arange(cap, dtype=np.int32),
                        np.ones((cap, width), np.float32))
        ep = ServingEndpoint(table_fn=lambda job: table, cache_mb=8,
                             window_ms=2.0)
        ep.start()
        lat_ms: "list[float]" = []
        lock = _th.Lock()
        threads_n, reads_per = 4, 40
        rng = np.random.default_rng(7)
        # skewed key draw: a hot head so the cache has something to do
        hot = rng.integers(0, 64, size=(threads_n, reads_per, 12))
        cold = rng.integers(0, cap, size=(threads_n, reads_per, 4))

        def client(i):
            sock = _sp.connect(("127.0.0.1", ep.port))
            try:
                mine = []
                for r in range(reads_per):
                    keys = np.concatenate(
                        [hot[i, r], cold[i, r]]).astype(np.int32)
                    t0 = time.perf_counter()
                    _sp.send_arrays(sock, {"op": "lookup", "r": r,
                                           "job": "bench", "mode": "live"},
                                    (keys,))
                    frame = _sp.recv_frame(sock)
                    dt = (time.perf_counter() - t0) * 1000.0
                    if frame and frame.get("op") == "rows":
                        mine.append(dt)
                with lock:
                    lat_ms.extend(mine)
            finally:
                sock.close()

        def storm():
            ths = [_th.Thread(target=client, args=(i,))
                   for i in range(threads_n)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout=120)

        # warmup: a full concurrent pass, so the coalesced gather
        # shapes the measured storm will hit are already compiled
        storm()
        with lock:
            lat_ms.clear()
        t0 = time.perf_counter()
        storm()
        wall = time.perf_counter() - t0
        st = ep.stats()
        ep.stop()
        if not lat_ms or wall <= 0:
            return None
        ordered = sorted(lat_ms)

        def pct(p):
            return ordered[min(len(ordered) - 1,
                               int(p * (len(ordered) - 1)))]

        cache = st.get("cache") or {}
        hits = cache.get("hits", 0)
        lookups = hits + cache.get("misses", 0)
        return {
            "qps": round(len(lat_ms) / wall, 1),
            "p50_ms": round(pct(0.50), 3),
            "p99_ms": round(pct(0.99), 3),
            "cache_hit_rate": (round(hits / lookups, 3)
                               if lookups else None),
            "batch_occupancy": st.get("batch_occupancy"),
        }
    except Exception:
        return None


def measure_lint() -> "dict | None":
    """harmonylint-suite runtime probe (tracked round over round in the
    BENCH json): one full run over harmony_tpu/. Returns {"lint.wall_ms",
    findings, suppressed, files, passes} or None — the bench line must
    never die for its lint hook."""
    try:
        from harmony_tpu.analysis import run_lint

        r = run_lint()
        return {
            "lint.wall_ms": r.wall_ms,
            "findings": len(r.findings),
            "suppressed": len(r.suppressed),
            "files": r.files_scanned,
            "passes": len(r.passes_run),
        }
    except Exception:
        return None


# -- machine-checked perf history (bench.py --compare) ---------------------
#
# The committed BENCH_r*.json trajectory was prose-reviewed until now: a
# regression only surfaced if a human read two JSON blobs side by side.
# `--compare` diffs the newest two rounds on the named headline series
# and exits 1 on a >threshold drop, so the history is machine-checked
# (bin/bench_diff.sh wraps it; tests/test_bench_compare.py runs it as a
# tier-1 smoke over the committed rounds).

#: higher-is-better series checked by default. `value` is the headline
#: aggregate; `cpu_rate` is the always-measurable denominator that keeps
#: rounds comparable when the accelerator transport is wedged;
#: `input_service.svc_sps` (dotted = nested lookup) tracks the
#: disaggregated-input-service serving rate — absent in rounds before
#: PR 10, which --compare skips rather than fails; the `autoscale.*`
#: pair tracks the closed policy loop (aggregate samples/sec and SLO
#: attainment of the churning-mix act arm) — absent before PR 15,
#: skipped the same way; `async_step.b1_sps` tracks the bounded-
#: staleness overlap arm (absent before PR 16, skipped the same way);
#: `chaos.scenarios_ok` tracks the seeded chaos smoke pair — any drop
#: means an invariant went red on a pinned schedule (absent before
#: PR 18, skipped the same way); `obs_incidents.recall` tracks the
#: incident engine's synthetic correlation probe — a drop means seeded
#: fault→diagnosis→action→resolution episodes stopped folding into
#: resolved incidents (absent before PR 19, skipped the same way); the
#: `serving.*` pair tracks the online read path (absent before PR 20,
#: skipped the same way) — serving.qps is higher-is-better like the
#: rest, serving.p99_ms is in LOWER_IS_BETTER so --compare fails on a
#: latency RISE, not a drop.
HEADLINE_SERIES = ("value", "cpu_rate", "input_service.svc_sps",
                   "autoscale.agg_sps", "autoscale.slo_attainment",
                   "async_step.b1_sps", "chaos.scenarios_ok",
                   "obs_incidents.recall", "serving.qps",
                   "serving.p99_ms")
#: series where a smaller number is the good direction (latencies):
#: compare_bench inverts the regression test for these
LOWER_IS_BETTER = frozenset({"serving.p99_ms"})
COMPARE_THRESHOLD = 0.15


def _bench_line(path: str) -> dict:
    """The result line of one committed round — either the bare JSON
    line bench.py prints or the driver's wrapper with it under
    "parsed"."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"]
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a bench line")
    return data


def _series_value(line: dict, name: str):
    """The measured number for one series, or (None, reason) when the
    round holds no measurement for it. Dotted names index nested dicts
    (``input_service.svc_sps``). 0.0 counts as a MEASUREMENT only
    when the line does not carry the unreachable-accelerator markers —
    the emit() convention reserves 0.0-with-error for 'did not run'."""
    v: "object | None" = line
    for part in name.split("."):
        if not isinstance(v, dict):
            v = None
            break
        v = v.get(part)
    if v is None:
        return None, "series absent"
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None, f"non-numeric {v!r}"
    unreachable = ("error" in line
                   or line.get("accelerator") == "unreachable")
    if v <= 0.0 and unreachable:
        return None, "unreachable-accelerator round (0.0 is not a measurement)"
    return v, None


def find_bench_rounds(root: "str | None" = None) -> "list[str]":
    """Committed BENCH_r*.json beside this file (or under ``root``),
    ordered oldest -> newest by round number."""
    import glob
    import re

    root = root or os.path.dirname(os.path.abspath(__file__))

    def round_of(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    files = [p for p in glob.glob(os.path.join(root, "BENCH_r*.json"))
             if round_of(p) >= 0]
    return sorted(files, key=round_of)


def compare_bench(old_path: str, new_path: str,
                  series=HEADLINE_SERIES,
                  threshold: float = COMPARE_THRESHOLD) -> dict:
    """Diff two committed rounds on the named headline series. A series
    REGRESSES when both rounds measured it and the new value moved more
    than ``threshold`` in the BAD direction — below the old for the
    default higher-is-better series, above it for LOWER_IS_BETTER ones
    (latencies); a series only one round measured is reported as
    skipped (with the reason), never failed — an unreachable
    accelerator is a transport state, not a code regression."""
    old_line, new_line = _bench_line(old_path), _bench_line(new_path)
    report = {
        "old": os.path.basename(old_path),
        "new": os.path.basename(new_path),
        "threshold": threshold,
        "series": {},
        "regressions": [],
    }
    for name in series:
        old_v, old_why = _series_value(old_line, name)
        new_v, new_why = _series_value(new_line, name)
        row: dict = {"old": old_v, "new": new_v}
        if old_v is None or new_v is None:
            row["status"] = "skipped"
            row["note"] = "; ".join(
                f"{side}: {why}" for side, why in
                (("old", old_why), ("new", new_why)) if why)
            report["series"][name] = row
            continue
        row["ratio"] = round(new_v / old_v, 4) if old_v else None
        if name in LOWER_IS_BETTER:
            row["direction"] = "lower-is-better"
            regressed = old_v > 0 and new_v > old_v * (1.0 + threshold)
        else:
            regressed = old_v > 0 and new_v < old_v * (1.0 - threshold)
        if regressed:
            row["status"] = "regression"
            report["regressions"].append(name)
        else:
            row["status"] = "ok"
        report["series"][name] = row
    report["ok"] = not report["regressions"]
    return report


def compare_main(argv) -> int:
    """`python bench.py --compare [--dir D] [--series a,b] [--threshold
    T] [OLD NEW]` — defaults to the newest two committed rounds. Exit:
    0 ok, 1 regression, 2 usage (fewer than two rounds / bad files)."""
    import argparse

    ap = argparse.ArgumentParser(prog="bench.py --compare")
    ap.add_argument("--compare", action="store_true")  # the mode flag
    ap.add_argument("--dir", default=None,
                    help="where the committed BENCH_r*.json live "
                         "(default: beside bench.py)")
    ap.add_argument("--series", default=",".join(HEADLINE_SERIES),
                    help="comma-separated headline series (higher=better "
                         "unless listed in LOWER_IS_BETTER)")
    ap.add_argument("--threshold", type=float, default=COMPARE_THRESHOLD,
                    help="allowed fractional drop before failing")
    ap.add_argument("files", nargs="*",
                    help="explicit OLD NEW round files (default: the "
                         "newest two in --dir)")
    args = ap.parse_args(argv)
    if args.files and len(args.files) != 2:
        print("--compare takes exactly two files (OLD NEW) or none",
              file=sys.stderr)
        return 2
    if args.files:
        old_path, new_path = args.files
    else:
        rounds = find_bench_rounds(args.dir)
        if len(rounds) < 2:
            print(f"--compare needs two committed rounds; found "
                  f"{len(rounds)}", file=sys.stderr)
            return 2
        old_path, new_path = rounds[-2], rounds[-1]
    series = [s.strip() for s in args.series.split(",") if s.strip()]
    try:
        report = compare_bench(old_path, new_path, series=series,
                               threshold=args.threshold)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"--compare: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


def main():
    enable_compile_cache()
    try:
        _platform, probe_log = probe_accelerator()
    except ProbeError as e:
        # Wedged transport: never touch the accelerator plugin in-process
        # (its init would hang this interpreter too) — pin to CPU and still
        # record the baseline pass so rounds stay comparable.
        jax.config.update("jax_platforms", "cpu")
        emit(0.0, cpu_baseline_rate(),
             error=f"accelerator unreachable after retries: {e}",
             probe_log=e.attempts_log)
        return
    try:
        accel = _discover_devices()
    except RuntimeError as e:  # probed fine but wedged since — same fallback
        jax.config.update("jax_platforms", "cpu")
        emit(0.0, cpu_baseline_rate(), error=f"accelerator unreachable: {e}",
             probe_log=probe_log)
        return
    print(f"accelerator devices: {accel}", file=sys.stderr)
    try:
        print("accelerator warmup (compile) pass:", file=sys.stderr)
        run_concurrent(accel, scale=1.0, epochs=1)
        print("concurrent MLR+NMF+LDA on accelerator:", file=sys.stderr)
        tpu_rate, tpu_walls = run_concurrent(accel, scale=1.0)
    except Exception as e:  # a half-dead transport must still yield a line
        emit(0.0, cpu_baseline_rate(),
             error=f"accelerator run failed: {type(e).__name__}: {e}",
             probe_log=probe_log)
        return
    emit(tpu_rate, cpu_baseline_rate(), job_walls=tpu_walls)


if __name__ == "__main__":
    if "--compare" in sys.argv[1:]:
        sys.exit(compare_main(sys.argv[1:]))
    main()
