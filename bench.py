#!/usr/bin/env python
"""Headline benchmark: MLR training throughput through the framework.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N}

The reference publishes no numbers (BASELINE.md: "published: {}"); its
north-star target is >=4x a CPU-cluster aggregate on PS workloads. So
``vs_baseline`` here is measured TPU samples/sec divided by the same
framework step running on this host's CPU backend — the honest local proxy
for "TPU vs CPU cluster": >=4.0 meets the north star.

Scale is an MLR job sized for one chip (the reference's example operating
point is 10 classes x 784 features on 5 tiny CPU executors; we bench a
heavier softmax regression that actually exercises the MXU).
"""
import json
import sys
import time

import jax

# Allow both the accelerator and CPU backends so the baseline runs in-process.
try:
    plats = jax.config.jax_platforms
    if plats and "cpu" not in plats:
        jax.config.update("jax_platforms", plats + ",cpu")
except Exception:
    pass

import numpy as np  # noqa: E402

from harmony_tpu.apps.mlr import MLRTrainer, make_synthetic  # noqa: E402
from harmony_tpu.config.params import TrainerParams  # noqa: E402
from harmony_tpu.dolphin import TrainerContext, TrainingDataProvider, WorkerTasklet  # noqa: E402
from harmony_tpu.metrics import MetricCollector, MetricManager  # noqa: E402
from harmony_tpu.parallel import build_mesh  # noqa: E402
from harmony_tpu.table import DenseTable, TableSpec  # noqa: E402

NUM_CLASSES = 64
NUM_FEATURES = 4096
FPP = 512
N_EXAMPLES = 32768
NUM_BATCHES = 8          # batch = 4096
WARM_EPOCHS = 1
MEASURE_EPOCHS = 3


def run(devices, epochs, n_examples=N_EXAMPLES, seed=0):
    """Train MLR through the framework; return steady-state samples/sec
    (excludes epoch 0: compile + H2D)."""
    mesh = build_mesh(devices)
    trainer = MLRTrainer(NUM_CLASSES, NUM_FEATURES, FPP, step_size=0.05)
    table = DenseTable(TableSpec(trainer.model_table_config()), mesh)
    params = TrainerParams(num_epochs=epochs, num_mini_batches=NUM_BATCHES)
    x, y = make_synthetic(n_examples, NUM_FEATURES, NUM_CLASSES, seed=seed)
    manager = MetricManager()
    manager.start_collection()
    worker = WorkerTasklet(
        "bench-mlr",
        TrainerContext(params=params, model_table=table),
        trainer,
        TrainingDataProvider([x, y], NUM_BATCHES),
        mesh,
        collector=MetricCollector(sink=manager.on_metric),
    )
    worker.run()
    steady = [m for m in manager.worker_batch_metrics() if m.epoch_idx >= WARM_EPOCHS]
    n = sum(m.num_examples for m in steady)
    t = sum(m.batch_time_sec for m in steady)
    return n / t if t > 0 else 0.0


def main():
    accel = jax.devices()  # default platform = the real chip(s) under the driver
    print(f"accelerator devices: {accel}", file=sys.stderr)
    tpu_rate = run(accel, WARM_EPOCHS + MEASURE_EPOCHS)
    print(f"accelerator: {tpu_rate:,.0f} samples/sec", file=sys.stderr)

    try:
        cpu = jax.devices("cpu")
        # Fewer epochs/examples on CPU — it only sets the denominator.
        cpu_rate = run(cpu[:1], 2, n_examples=N_EXAMPLES // 4, seed=1)
        print(f"cpu baseline: {cpu_rate:,.0f} samples/sec", file=sys.stderr)
    except Exception as e:  # pragma: no cover - cpu backend always present
        print(f"cpu baseline unavailable: {e}", file=sys.stderr)
        cpu_rate = 0.0

    vs = tpu_rate / cpu_rate if cpu_rate > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": "MLR training throughput (single-chip, fused pull/comp/push)",
                "value": round(tpu_rate, 1),
                "unit": "samples/sec",
                "vs_baseline": round(vs, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
